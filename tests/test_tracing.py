"""Distributed tracing on the native plane (ISSUE 15).

Covers the tentpole end to end:

- **fast path**: traced PRPC frames (RpcRequestMeta fields 3-6 + the
  field-9 sampled bit) are decoded by the C++ cutter and answered
  without the interpreter — ``cb_frames == 0`` under a traced flood;
- **wire byte-identity**: a ``NativeClientChannel`` traced request is
  byte-identical to ``baidu_std.pack_request`` with the same fields,
  and the native and Python server planes answer a traced request with
  identical bytes;
- **coherent sampling**: the head-based sampled bit rides the wire and
  overrides local election (token bucket AND the telemetry ring's 1/N);
- **drain parenting**: sampled native completions join the CALLER's
  trace (fresh ids only when the wire carried none);
- **fleet assembly**: client → server A → server B (B in a REAL second
  process) yields one trace id with parent→child links across all
  hops, pulled from both nodes by ``rpc_view --trace --targets``;
- **collective sessions**: every party's session span carries the
  proposer's trace id;
- the ``SpanStore.by_trace`` index (satellite 1) and the /hotspots
  503-with-retry hardening (satellite 6).
"""

from __future__ import annotations

import os
import socket
import struct
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))

from incubator_brpc_tpu.protocol import baidu_std  # noqa: E402
from incubator_brpc_tpu.protocol.tbus_std import Meta  # noqa: E402
from incubator_brpc_tpu.rpc import (  # noqa: E402
    Channel,
    ChannelOptions,
    Controller,
    Server,
    ServerOptions,
)
from incubator_brpc_tpu.transport import native_plane  # noqa: E402
from incubator_brpc_tpu.transport.native_plane import (  # noqa: E402
    NativeClientChannel,
    native_echo,
)

pytestmark = pytest.mark.skipif(
    not native_plane.NET_AVAILABLE, reason="native runtime unavailable"
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def native_server():
    created = []

    def make(services=None, options=None):
        opts = options or ServerOptions(
            native_plane=True, usercode_inline=True
        )
        opts.native_plane = True
        srv = Server(opts)
        for name, handlers in (services or {}).items():
            srv.add_service(name, handlers)
        created.append(srv)
        assert srv.start(0)
        assert srv._native_plane is not None
        return srv

    yield make
    for srv in created:
        srv.stop()


@pytest.fixture
def clean_spans():
    from incubator_brpc_tpu.builtin.rpcz import span_store

    span_store.clear()
    yield span_store
    span_store.clear()


def _read_prpc_frame(sock: socket.socket, buf: bytes = b"") -> bytes:
    while True:
        if len(buf) >= 12:
            total = 12 + struct.unpack(">I", buf[4:8])[0]
            if len(buf) >= total:
                return buf[:total]
        data = sock.recv(65536)
        assert data, "connection closed mid-frame"
        buf += data


TRACE_META = dict(
    log_id=7, trace_id=0x1F00DBEEF, span_id=0xABCDEF, parent_span_id=0x77,
    sampled=1,
)


class TestTracedWireByteIdentity:
    """Satellite: traced frames are byte-identical across the planes."""

    def test_native_client_traced_request_matches_pack_request(self):
        # capture the native client's traced request bytes on a raw
        # fake server; the call itself times out (never answered) —
        # only the emitted frame matters here
        lst = socket.socket()
        lst.bind(("127.0.0.1", 0))
        lst.listen(1)
        port = lst.getsockname()[1]
        nch = NativeClientChannel("127.0.0.1", port, protocol="baidu_std")
        try:
            rc, _err, _m, _b = nch.call(
                "svc", "echo", b"traced-payload", attachment=b"AT",
                timeout_ms=200, **TRACE_META,
            )
            assert rc < 0  # timed out: nobody answered
            conn, _ = lst.accept()
            conn.settimeout(5)
            wire = _read_prpc_frame(conn)
            conn.close()
        finally:
            nch.close()
            lst.close()
        # the cid is the channel's to mint: decode it, then the WHOLE
        # frame must equal the Python packer's output for those fields
        rm = baidu_std.RpcMeta.decode(
            wire[12:12 + struct.unpack(">I", wire[8:12])[0]]
        )
        assert rm.trace_id == TRACE_META["trace_id"]
        assert rm.span_id == TRACE_META["span_id"]
        assert rm.parent_span_id == TRACE_META["parent_span_id"]
        assert rm.log_id == TRACE_META["log_id"]
        assert rm.sampled == 1
        expected = baidu_std.pack_request(
            # timeout_ms: the native client stamps the propagated
            # deadline (field 8) from the call's budget — part of the
            # byte-identical submessage
            Meta(service="svc", method="echo", timeout_ms=200, **TRACE_META),
            b"traced-payload",
            correlation_id=rm.correlation_id,
            attachment=b"AT",
        )
        assert wire == expected

    def test_native_and_python_servers_answer_traced_identically(
        self, native_server
    ):
        req = baidu_std.pack_request(
            Meta(service="svc", method="echo", **TRACE_META),
            b"traced", correlation_id=55,
        )

        def roundtrip(port):
            s = socket.create_connection(("127.0.0.1", port))
            try:
                s.settimeout(10)
                s.sendall(req)
                return _read_prpc_frame(s)
            finally:
                s.close()

        nsrv = native_server({"svc": {"echo": native_echo}})
        native_resp = roundtrip(nsrv.port)
        stats = nsrv._native_plane.stats()
        assert stats["native_reqs"] >= 1 and stats["cb_frames"] == 0, (
            "a traced request fell off the interpreter-free plane"
        )
        psrv = Server(ServerOptions(usercode_inline=True))
        psrv.add_service("svc", {"echo": native_echo})
        assert psrv.start(0)
        try:
            python_resp = roundtrip(psrv.port)
        finally:
            psrv.stop()
        assert native_resp == python_resp

    def test_traced_tbus_frame_stays_native(self, native_server):
        # the tbus JSON scanner decodes the same keys natively
        srv = native_server({"svc": {"echo": native_echo}})
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{srv.port}",
            options=ChannelOptions(native_plane=True),  # tbus_std wire
        )
        cntl = Controller()
        cntl.trace_id = 0x5151
        cntl.span_id = 0x52
        cntl.trace_sampled = 1
        c = ch.call_method("svc", "echo", b"t", cntl=cntl)
        assert c.ok(), c.error_text
        stats = srv._native_plane.stats()
        assert stats["native_reqs"] >= 1
        assert stats["cb_frames"] == 0


class TestTracedFloodStaysNative:
    """Acceptance: a traced PRPC flood is interpreter-free — the pump's
    counter-scheduled traced template included."""

    def test_traced_pump_zero_cb_frames(self, native_server, tuned_flags,
                                        clean_spans):
        tuned_flags("enable_rpcz", True)
        srv = native_server({"svc": {"echo": native_echo}})
        nch = NativeClientChannel("127.0.0.1", srv.port, protocol="baidu_std")
        try:
            nch.set_trace(
                trace_id=0xF00D, span_id=100, parent_span_id=9,
                sampled=1, every=1,
            )
            nch.pump("svc", "echo", b"x" * 64, 3000, inflight=32)
        finally:
            nch.close()
        stats = srv._native_plane.stats()
        assert stats["native_reqs"] >= 3000
        assert stats["cb_frames"] == 0
        srv._native_plane.drain_telemetry()
        spans = clean_spans.by_trace(0xF00D)
        # every frame carried the sampled bit; spans are bounded only by
        # the ring (drops under a full-rate pump are the documented
        # overflow discipline), so SOME — typically most — survive
        assert len(spans) > 100
        # per-frame distinct span ids parent the server spans
        assert len({sp.parent_span_id for sp in spans}) == len(spans)

    def test_traced_pump_close_to_bare_pump(self, native_server,
                                            tuned_flags):
        # same-run ratio gate with a deliberately generous bound: the
        # bench row (prpc_traced_pump_ns, acceptance ~1.15x) carries the
        # honest number with host calibration; HERE the tripwire is the
        # catastrophic regression — traced frames falling back to the
        # interpreter route is a >10x cliff, so 2x catches it through
        # shared-container noise without flaking
        tuned_flags("enable_rpcz", False)  # isolate the wire/record cost
        srv = native_server({"svc": {"echo": native_echo}})
        nch = NativeClientChannel("127.0.0.1", srv.port, protocol="baidu_std")
        try:
            nch.pump("svc", "echo", b"x" * 64, 2000, inflight=64)  # warm
            bare = min(
                nch.pump("svc", "echo", b"x" * 64, 20000, inflight=64)
                for _ in range(3)
            )
            nch.set_trace(trace_id=0xBEE, span_id=1, sampled=1, every=1)
            traced = min(
                nch.pump("svc", "echo", b"x" * 64, 20000, inflight=64)
                for _ in range(3)
            )
        finally:
            nch.close()
        assert srv._native_plane.stats()["cb_frames"] == 0
        assert traced < bare * 2.0, (
            f"traced pump {traced:.0f} ns vs bare {bare:.0f} ns — traced "
            "traffic is no longer near the fast path"
        )

    def test_set_trace_rejected_on_tbus_channel(self, native_server):
        srv = native_server({"svc": {"echo": native_echo}})
        nch = NativeClientChannel("127.0.0.1", srv.port)  # tbus_std
        try:
            with pytest.raises(ValueError):
                nch.set_trace(trace_id=1, every=1)
        finally:
            nch.close()


class TestCoherentSampling:
    """The head-based sampled bit overrides every local election."""

    def test_wire_sampled_bit_overrides_ring_election(
        self, native_server, tuned_flags, clean_spans
    ):
        # local 1/N election effectively off (huge N): only the wire
        # bit can sample — and it must, on every traced request
        tuned_flags("enable_rpcz", True)
        tuned_flags("native_telemetry_sample_every", 1_000_000)
        srv = native_server({"svc": {"echo": native_echo}})
        nch = NativeClientChannel("127.0.0.1", srv.port, protocol="baidu_std")
        try:
            for i in range(50):
                rc, err, _m, _b = nch.call(
                    "svc", "echo", b"x", trace_id=0xCAFE, span_id=i + 1,
                    sampled=1, timeout_ms=2000,
                )
                assert rc >= 0 and err == 0
            # unsampled traced calls: ids propagate, no forced span
            for i in range(50):
                rc, err, _m, _b = nch.call(
                    "svc", "echo", b"x", trace_id=0xD00D, span_id=i + 1,
                    timeout_ms=2000,
                )
                assert rc >= 0 and err == 0
        finally:
            nch.close()
        srv._native_plane.drain_telemetry()
        assert len(clean_spans.by_trace(0xCAFE)) == 50
        assert len(clean_spans.by_trace(0xD00D)) == 0
        assert srv._native_plane.stats()["cb_frames"] == 0

    def test_forced_records_survive_refused_elected_ones(
        self, native_server, tuned_flags, clean_spans
    ):
        # regression (review find): with the token bucket dry, a
        # locally-ELECTED record ahead of a wire-FORCED one in the same
        # drain batch must not end the scan — the forced span still
        # submits (continue, not break)
        tuned_flags("enable_rpcz", True)
        tuned_flags("native_telemetry_sample_every", 2)  # elect plenty
        tuned_flags("rpcz_samples_per_second", 0.000001)  # bucket dry
        srv = native_server({"svc": {"echo": native_echo}})
        nch = NativeClientChannel("127.0.0.1", srv.port, protocol="baidu_std")
        try:
            for i in range(20):
                # untraced (election fodder) then traced+forced
                rc, err, _m, _b = nch.call("svc", "echo", b"x",
                                           timeout_ms=2000)
                assert rc >= 0 and err == 0
                rc, err, _m, _b = nch.call(
                    "svc", "echo", b"x", trace_id=0xFACE, span_id=i + 1,
                    sampled=1, timeout_ms=2000,
                )
                assert rc >= 0 and err == 0
        finally:
            nch.close()
        srv._native_plane.drain_telemetry()
        assert len(clean_spans.by_trace(0xFACE)) == 20

    def test_server_span_forced_by_meta_sampled(self, tuned_flags):
        # Python-plane twin of the ring override: a drained token bucket
        # refuses unforced spans but MUST honor the wire's sampled bit
        from incubator_brpc_tpu.builtin import rpcz

        tuned_flags("enable_rpcz", True)
        # grab() clamps tokens to min(rate, ...): the tiny rate makes
        # the shared bucket dry from the next call on, no drain loop
        tuned_flags("rpcz_samples_per_second", 0.000001)

        class _C:
            _request_payload = b""

        meta_plain = Meta(service="s", method="m", trace_id=5, span_id=6)
        meta_forced = Meta(
            service="s", method="m", trace_id=5, span_id=6, sampled=1
        )
        assert rpcz.start_server_span(_C(), meta_plain) is None
        span = rpcz.start_server_span(_C(), meta_forced)
        assert span is not None
        assert span.trace_id == 5 and span.parent_span_id == 6
        rpcz.clear_parent_span(span)

    def test_client_span_decides_sampled_bit_once(self, tuned_flags):
        # the edge that samples stamps sampled=1; inside a serving span
        # the bit propagates even when this hop's bucket is dry
        from incubator_brpc_tpu.builtin import rpcz

        tuned_flags("enable_rpcz", True)
        # a refill-rate high enough that the shared bucket (possibly
        # drained by an earlier test) regains a token within the clock
        # resolution of the grab itself
        tuned_flags("rpcz_samples_per_second", 10_000_000)
        time.sleep(0.01)

        class _C:
            _request_payload = b""
            _service = "s"
            _method = "m"
            log_id = 0
            trace_id = 0
            span_id = 0
            parent_span_id = 0
            trace_sampled = 0

        c1 = _C()
        span = rpcz.start_client_span(c1)
        assert span is not None and c1.trace_sampled == 1
        # dry bucket, no ambient parent: no span, no sampled bit.  The
        # tiny rate FIRST: grab() clamps tokens to min(rate, ...), so
        # the bucket is dry from the next call on (draining by looping
        # at a high refill rate would never terminate)
        tuned_flags("rpcz_samples_per_second", 0.000001)
        c2 = _C()
        assert rpcz.start_client_span(c2) is None
        assert c2.trace_sampled == 0
        # dry bucket but inside a serving span: the bit still propagates
        meta = Meta(service="s", method="m", trace_id=9, span_id=8, sampled=1)
        server_span = rpcz.start_server_span(_C(), meta)
        assert server_span is not None
        try:
            c3 = _C()
            assert rpcz.start_client_span(c3) is None  # bucket still dry
            assert c3.trace_sampled == 1
            assert c3.trace_id == 9
            assert c3.parent_span_id == server_span.span_id
        finally:
            rpcz.clear_parent_span(server_span)


class TestSpanStoreTraceIndex:
    """Satellite 1: by_trace is index-backed, exact across eviction."""

    def _span(self, trace, span_id, start=1):
        from incubator_brpc_tpu.builtin.rpcz import Span

        return Span(
            trace_id=trace, span_id=span_id, start_real_us=start
        )

    def test_index_tracks_submit_and_ring_eviction(self, tuned_flags,
                                                   clean_spans):
        tuned_flags("rpcz_max_spans", 10)
        store = clean_spans
        for i in range(10):
            store.submit(self._span(1000 + i, i + 1))
        assert [sp.span_id for sp in store.by_trace(1000)] == [1]
        # the ring is full: the next submit evicts trace 1000's span
        store.submit(self._span(2000, 99))
        assert store.by_trace(1000) == []
        assert [sp.span_id for sp in store.by_trace(2000)] == [99]
        # several spans of ONE trace accumulate in order
        for i in range(3):
            store.submit(self._span(3000, 200 + i))
        assert [sp.span_id for sp in store.by_trace(3000)] == [200, 201, 202]

    def test_index_survives_maxlen_shrink_and_clear(self, tuned_flags,
                                                    clean_spans):
        store = clean_spans
        tuned_flags("rpcz_max_spans", 100)
        for i in range(20):
            store.submit(self._span(7000, i + 1))
        tuned_flags("rpcz_max_spans", 5)
        store.submit(self._span(7000, 500))
        kept = store.by_trace(7000)
        assert [sp.span_id for sp in kept] == [17, 18, 19, 20, 500]
        store.clear()
        assert store.by_trace(7000) == []
        assert len(store) == 0

    def test_index_matches_scan_semantics(self, tuned_flags, clean_spans):
        # oracle: the index answers exactly what the old O(n) scan did
        import random

        rng = random.Random(99)
        tuned_flags("rpcz_max_spans", 50)
        store = clean_spans
        for i in range(300):
            store.submit(self._span(rng.randrange(1, 9), i + 1))
        with store._lock:
            ring = list(store._spans)
        for t in range(1, 9):
            assert store.by_trace(t) == [
                sp for sp in ring if sp.trace_id == t
            ]
        # trace id 0 means "untraced": never indexed, never queryable
        assert store.by_trace(0) == []


def _start_node_b(tmp_path):
    """A REAL second process running a native-plane echo server with
    rpcz on — the second live node of the fleet-assembly acceptance."""
    import subprocess

    script = tmp_path / "node_b.py"
    script.write_text(
        "import sys, time\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from incubator_brpc_tpu.utils.flags import set_flag_unchecked\n"
        "set_flag_unchecked('enable_rpcz', True)\n"
        "set_flag_unchecked('native_telemetry_drain_ms', 20)\n"
        "from incubator_brpc_tpu.rpc import Server, ServerOptions\n"
        "from incubator_brpc_tpu.transport.native_plane import native_echo\n"
        "srv = Server(ServerOptions(native_plane=True, usercode_inline=True))\n"
        "srv.add_service('svc', {'echo': native_echo})\n"
        "assert srv.start(0)\n"
        "print(srv.port, flush=True)\n"
        "time.sleep(120)\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, str(script)],
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        env=env,
        text=True,
    )
    line = proc.stdout.readline().strip()
    if not line.isdigit():
        proc.kill()
        pytest.skip("node B failed to start")
    return proc, int(line)


class TestMultiHopFleetAssembly:
    """Acceptance: client → server A → server B (B natively dispatched,
    in a second PROCESS) yields one trace id with parent→child links
    across every hop, assembled by rpc_view --trace from two live
    nodes."""

    def test_one_trace_across_two_processes(self, tmp_path, tuned_flags,
                                            clean_spans):
        tuned_flags("enable_rpcz", True)
        proc_b, port_b = _start_node_b(tmp_path)
        srv_a = None
        try:
            down = Channel()
            assert down.init(
                f"127.0.0.1:{port_b}",
                options=ChannelOptions(
                    native_plane=True, protocol="baidu_std"
                ),
            )

            def relay(cntl, request):
                # hop A: a Python handler cascading to B — the nested
                # call inherits A's server span as parent (thread-local)
                c = down.call_method("svc", "echo", request)
                assert c.ok(), c.error_text
                return c.response_payload

            srv_a = Server(ServerOptions(usercode_inline=True))
            srv_a.add_service("front", {"relay": relay})
            assert srv_a.start(0)

            edge = Channel()
            assert edge.init(f"127.0.0.1:{srv_a.port}")
            cntl = Controller(timeout_ms=10000)
            c = edge.call_method("front", "relay", b"fleet", cntl=cntl)
            assert c.ok(), c.error_text
            trace_id = cntl.trace_id
            assert trace_id != 0

            # node B's background drain parents its native server span;
            # poll both nodes' /rpcz until the trace is complete
            from tools.rpc_view import scrape_rpcz

            deadline = time.monotonic() + 15
            spans_a = spans_b = []
            while time.monotonic() < deadline:
                try:
                    spans_a = scrape_rpcz(
                        f"127.0.0.1:{srv_a.port}", f"{trace_id:x}"
                    )
                    spans_b = scrape_rpcz(
                        f"127.0.0.1:{port_b}", f"{trace_id:x}"
                    )
                except OSError:
                    spans_a = spans_b = []
                if spans_b and len(spans_a) >= 3:
                    break
                time.sleep(0.1)
            assert spans_b, "node B never surfaced the traced hop"
            # every hop shares the ONE trace id
            for sp in spans_a + spans_b:
                assert sp.trace_id == trace_id
            # parent→child links across the hops: A's server span is the
            # edge client span's child; A's downstream client span is
            # A's server span's child; B's server span parents to A's
            # downstream client span — all stitched by span ids
            by_id = {sp.span_id: sp for sp in spans_a}
            a_client = [
                sp for sp in spans_a
                if sp.span_type == "client" and sp.parent_span_id in by_id
            ]
            assert a_client, "A's nested client span must parent to A's span"
            b_server = spans_b[0]
            assert any(
                b_server.parent_span_id == sp.span_id for sp in spans_a
            ), "B's span must be a child of a span on node A"

            # the fleet puller renders the merged cross-process tree
            from tools.rpc_view import main as view_main

            rc = view_main([
                "--trace", f"{trace_id:x}",
                "--targets",
                f"127.0.0.1:{srv_a.port},127.0.0.1:{port_b}",
            ])
            assert rc == 0
        finally:
            if srv_a is not None:
                srv_a.stop()
            proc_b.kill()
            proc_b.wait(timeout=10)


class TestHotspotsRetry:
    """Satellite 6: /hotspots answers 503-with-Retry-After while a run
    holds the profile lock, and remote windows are clamped."""

    def test_profile_in_progress_is_503_with_retry(self):
        import threading

        from incubator_brpc_tpu.builtin import hotspots, pages

        class _Frame:
            path = "/hotspots"
            query = {"seconds": "0.2"}
            method = "GET"
            headers = {}

        started = threading.Event()

        def hold():
            with hotspots._profile_lock:
                hotspots._profile_until = time.monotonic() + 0.5
                started.set()
                time.sleep(0.4)
            hotspots._profile_until = 0.0

        t = threading.Thread(target=hold)
        t.start()
        started.wait(5)
        try:
            resp = pages._hotspots(None, _Frame())
        finally:
            t.join()
        assert resp[0] == 503
        assert len(resp) == 4 and "Retry-After" in resp[3]
        assert int(resp[3]["Retry-After"]) >= 1

    def test_seconds_clamped(self, monkeypatch):
        from incubator_brpc_tpu.builtin import hotspots, pages

        seen = {}

        def fake_sample(seconds):
            seen["seconds"] = seconds
            return {"samples": 0, "stacks": [], "flat": []}

        monkeypatch.setattr(hotspots, "sample_cpu", fake_sample)

        class _Frame:
            path = "/hotspots"
            query = {"seconds": "600"}
            method = "GET"
            headers = {}

        status, _ctype, _body = pages._hotspots(None, _Frame())
        assert status == 200
        assert seen["seconds"] == 10.0
        _Frame.query = {"seconds": "nan"}
        assert pages._hotspots(None, _Frame())[0] == 400

    def test_retry_after_header_reaches_the_wire(self, native_server):
        import threading

        from incubator_brpc_tpu.builtin import hotspots
        from incubator_brpc_tpu.protocol.http import http_call

        srv = native_server({"svc": {"echo": native_echo}})
        started = threading.Event()

        def hold():
            with hotspots._profile_lock:
                hotspots._profile_until = time.monotonic() + 1.0
                started.set()
                time.sleep(0.8)
            hotspots._profile_until = 0.0

        t = threading.Thread(target=hold)
        t.start()
        started.wait(5)
        try:
            status, headers, _body = http_call(
                "127.0.0.1", srv.port, "/hotspots?seconds=0.2", timeout=10
            )
        finally:
            t.join()
        assert status == 503
        assert "retry-after" in {k.lower() for k in headers}
