"""L3 transport tests — loopback client↔server over real TCP, the same
in-process shape the reference uses (test/brpc_socket_unittest.cpp,
brpc_event_dispatcher_unittest.cpp): contended writers proving the
single-drainer contract, EAGAIN/KeepWrite on multi-MB writes, set_failed
semantics (pending-write callbacks, versioned address), health-check
revival against a restarted listener, and InputMessenger cut behavior on
fragmented/garbage input."""

import socket as pysocket
import threading
import time

import pytest

from incubator_brpc_tpu.iobuf import IOBuf
from incubator_brpc_tpu import protocol as proto_pkg
from incubator_brpc_tpu.protocol import tbus_std
from incubator_brpc_tpu.protocol.tbus_std import (
    FLAG_RESPONSE,
    Meta,
    pack_frame,
)
from incubator_brpc_tpu.transport import (
    Acceptor,
    InputMessenger,
    Socket,
    SocketMap,
    address_socket,
)
from incubator_brpc_tpu.utils.endpoint import EndPoint
from incubator_brpc_tpu.utils.flags import flag_registry
from incubator_brpc_tpu.utils.status import ErrorCode

LOOP = "127.0.0.1"


def _echo_handler(sock, frame, proto):
    """Server side: echo the payload back, marked as a response."""
    out = pack_frame(
        frame.meta,
        frame.payload,
        frame.correlation_id,
        flags=FLAG_RESPONSE,
        attachment=frame.attachment,
    )
    sock.write(out)


class _Client:
    """Collects responses by correlation id."""

    def __init__(self, endpoint):
        self.responses = {}
        self.cv = threading.Condition()
        self.sock = Socket.connect(
            endpoint,
            messenger=InputMessenger(),
            health_check_interval=0.1,
        )
        self.sock.user_message_handler = self._on_msg

    def _on_msg(self, sock, frame, proto):
        with self.cv:
            self.responses[frame.correlation_id] = frame
            self.cv.notify_all()

    def call(self, payload: bytes, cid: int, timeout=5.0):
        data = pack_frame(Meta(service="echo", method="echo"), payload, cid)
        rc = self.sock.write(data)
        assert rc == 0, f"write failed: {rc}"
        return self.wait(cid, timeout)

    def wait(self, cid: int, timeout=5.0):
        deadline = time.monotonic() + timeout
        with self.cv:
            while cid not in self.responses:
                left = deadline - time.monotonic()
                assert left > 0, f"timeout waiting for cid {cid}"
                self.cv.wait(left)
            return self.responses.pop(cid)


@pytest.fixture()
def echo_server():
    acceptor = Acceptor(
        EndPoint(ip=LOOP, port=0),
        messenger=InputMessenger(),
        user_message_handler=_echo_handler,
    )
    yield acceptor
    acceptor.stop()


def test_echo_roundtrip(echo_server):
    c = _Client(f"{LOOP}:{echo_server.port}")
    try:
        frame = c.call(b"hello tpu fabric", cid=1)
        assert frame.payload == b"hello tpu fabric"
        assert frame.is_response
        # preferred protocol remembered after first cut
        assert c.sock.preferred_protocol is proto_pkg.TBUS_STD
    finally:
        c.sock.recycle()


def test_large_payload_exercises_keepwrite(echo_server):
    c = _Client(f"{LOOP}:{echo_server.port}")
    try:
        import os

        payload = os.urandom(8 * 1024 * 1024)  # far beyond one writev
        frame = c.call(payload, cid=7, timeout=30.0)
        assert frame.payload == payload
    finally:
        c.sock.recycle()


def test_attachment_survives_transport(echo_server):
    c = _Client(f"{LOOP}:{echo_server.port}")
    try:
        att = b"A" * 1000
        data = pack_frame(
            Meta(service="echo", method="echo"), b"payload", 42, attachment=att
        )
        assert c.sock.write(data) == 0
        frame = c.wait(42)
        assert frame.payload == b"payload"
        assert frame.attachment == att
    finally:
        c.sock.recycle()


def test_contended_writers_single_drainer(echo_server):
    """32 threads × 8 writes each on ONE socket: every frame must arrive
    intact (interleaved writev from two threads would corrupt framing)."""
    c = _Client(f"{LOOP}:{echo_server.port}")
    try:
        nthreads, neach = 32, 8
        errs = []

        def hammer(tid):
            for i in range(neach):
                cid = tid * 1000 + i
                payload = bytes([tid]) * (100 + i * 997)
                data = pack_frame(Meta(service="e", method="e"), payload, cid)
                rc = c.sock.write(data)
                if rc != 0:
                    errs.append((cid, rc))

        threads = [
            threading.Thread(target=hammer, args=(t,)) for t in range(nthreads)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        for tid in range(nthreads):
            for i in range(neach):
                cid = tid * 1000 + i
                frame = c.wait(cid, timeout=30.0)
                assert frame.payload == bytes([tid]) * (100 + i * 997)
    finally:
        c.sock.recycle()


def test_versioned_address_and_set_failed():
    acceptor = Acceptor(EndPoint(ip=LOOP, port=0), messenger=InputMessenger())
    try:
        sock = Socket.connect(
            f"{LOOP}:{acceptor.port}", health_check_interval=0
        )
        sid = sock.id
        assert address_socket(sid) is sock
        # pending write failed with callback on set_failed
        failures = []
        sock.set_failed(ErrorCode.EFAILEDSOCKET, "test kill")
        assert address_socket(sid) is None  # Address-after-SetFailed contract
        assert sock.write(b"x", on_error=lambda c, m: failures.append(c)) != 0
        sock.recycle()
        assert address_socket(sid) is None
    finally:
        acceptor.stop()


def test_write_on_error_callback_on_failure():
    # server end that never reads: fill the pipe then kill the socket
    lsock = pysocket.socket()
    lsock.bind((LOOP, 0))
    lsock.listen(1)
    port = lsock.getsockname()[1]
    sock = Socket.connect(f"{LOOP}:{port}", health_check_interval=0)
    conn, _ = lsock.accept()
    try:
        conn.setsockopt(pysocket.SOL_SOCKET, pysocket.SO_RCVBUF, 4096)
        failed = []
        # flood until the kernel buffer jams, then fail the socket: queued
        # requests must see their on_error callbacks
        for _ in range(200):
            sock.write(b"z" * 65536, on_error=lambda c, m: failed.append(c))
        sock.set_failed(ErrorCode.EFAILEDSOCKET, "killed by test")
        deadline = time.monotonic() + 5
        while not failed and time.monotonic() < deadline:
            time.sleep(0.01)
        assert failed, "queued writes were not failed"
        assert all(c == ErrorCode.EFAILEDSOCKET for c in failed)
    finally:
        conn.close()
        lsock.close()
        sock.recycle()


def test_eof_fails_socket(echo_server):
    c = _Client(f"{LOOP}:{echo_server.port}")
    c.sock.health_check_interval = 0  # no revive: observe the failure
    c.call(b"warm", cid=1)
    for s in echo_server.connections():
        s.set_failed(ErrorCode.ECLOSE, "server closing")
    deadline = time.monotonic() + 5
    while c.sock.state == 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert c.sock.state != 0
    assert c.sock.error_code in (ErrorCode.EEOF, ErrorCode.EFAILEDSOCKET)
    c.sock.recycle()


def test_health_check_revives_against_restarted_server():
    acceptor = Acceptor(
        EndPoint(ip=LOOP, port=0),
        messenger=InputMessenger(),
        user_message_handler=_echo_handler,
    )
    port = acceptor.port
    c = _Client(f"{LOOP}:{port}")
    try:
        assert c.call(b"one", cid=1).payload == b"one"
        acceptor.stop()  # kills the connection under the client
        deadline = time.monotonic() + 5
        while c.sock.state == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert c.sock.state != 0
        # restart a listener on the same port; health checker (0.1 s)
        # revives. Rebinding can transiently fail (TIME_WAIT / a shared CI
        # host racing the port) — retry, and skip if the port is truly gone
        acceptor2 = None
        rebind_deadline = time.monotonic() + 5
        while acceptor2 is None:
            try:
                acceptor2 = Acceptor(
                    EndPoint(ip=LOOP, port=port),
                    messenger=InputMessenger(),
                    user_message_handler=_echo_handler,
                )
            except OSError:
                if time.monotonic() > rebind_deadline:
                    pytest.skip("port could not be rebound on this host")
                time.sleep(0.1)
        try:
            deadline = time.monotonic() + 10
            while c.sock.state != 0 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert c.sock.state == 0, "socket did not revive"
            assert c.call(b"after revival", cid=2).payload == b"after revival"
        finally:
            acceptor2.stop()
    finally:
        c.sock.recycle()


def test_garbage_input_fails_connection(echo_server):
    raw = pysocket.create_connection((LOOP, echo_server.port))
    try:
        # matches no registered protocol (tbus_std magic is "TPRC"; not an
        # HTTP method line either)
        raw.sendall(b"\x00\xffGARBAGE-ON-THE-WIRE\r\n\r\n")
        # server must drop us: recv sees EOF
        raw.settimeout(5)
        assert raw.recv(4096) == b""
    finally:
        raw.close()


def test_unix_domain_socket_rpc(tmp_path):
    """unix:// endpoints work end to end (reference butil/unix_socket.cpp
    + Server listening on a unix path)."""
    from incubator_brpc_tpu.rpc import Channel, Server

    path = str(tmp_path / "echo.sock")
    server = Server()
    server.add_service("u", {"echo": lambda c, r: r[::-1]})
    assert server.start(f"unix://{path}")
    try:
        ch = Channel()
        assert ch.init(f"unix://{path}")
        cntl = ch.call_method("u", "echo", b"abcdef")
        assert cntl.ok(), cntl.error_text
        assert cntl.response_payload == b"fedcba"
    finally:
        server.stop()
        server.join(timeout=5)


def test_unix_socket_lifecycle(tmp_path):
    """stop() unlinks the path; a live listener can't be hijacked; a stale
    file from a dead server is cleaned and rebound."""
    import os

    from incubator_brpc_tpu.rpc import Channel, Server

    path = str(tmp_path / "life.sock")
    a = Server()
    a.add_service("u", {"e": lambda c, r: r})
    assert a.start(f"unix://{path}")
    # a second bind on a LIVE path must fail loudly, not hijack
    b = Server()
    b.add_service("u", {"e": lambda c, r: r})
    with pytest.raises(OSError):
        b.start(f"unix://{path}")
    a.stop()
    a.join(timeout=5)
    assert not os.path.exists(path)  # clean shutdown removed the file
    # a stale file from a crashed server is unlinked and rebound
    open(path, "w").close()  # not even a socket: bind must still work? no —
    os.unlink(path)  # (plain files are not probe-able sockets; keep it real)
    import socket as pysock

    dead = pysock.socket(pysock.AF_UNIX, pysock.SOCK_STREAM)
    dead.bind(path)
    dead.close()  # bound then closed WITHOUT unlink: classic stale file
    c = Server()
    c.add_service("u", {"e": lambda cx, r: r + r})
    assert c.start(f"unix://{path}")
    try:
        ch = Channel()
        assert ch.init(f"unix://{path}")
        assert ch.call_method("u", "e", b"xy").response_payload == b"xyxy"
    finally:
        c.stop()
        c.join(timeout=5)


def test_fragmented_frame_reassembles(echo_server):
    """Resumable cut: a frame dribbled in 7-byte chunks still parses."""
    raw = pysocket.create_connection((LOOP, echo_server.port))
    try:
        data = pack_frame(Meta(service="e", method="e"), b"fragmented-payload", 99)
        for i in range(0, len(data), 7):
            raw.sendall(data[i : i + 7])
            time.sleep(0.002)
        raw.settimeout(5)
        got = b""
        want = None
        while True:
            got += raw.recv(65536)
            frame, consumed = tbus_std.try_parse_frame(got)
            if frame is not None:
                want = frame
                break
        assert want.payload == b"fragmented-payload"
        assert want.correlation_id == 99
    finally:
        raw.close()


def test_overcrowded_backpressure():
    lsock = pysocket.socket()
    lsock.bind((LOOP, 0))
    lsock.listen(1)
    port = lsock.getsockname()[1]
    sock = Socket.connect(f"{LOOP}:{port}", health_check_interval=0)
    conn, _ = lsock.accept()
    old = flag_registry.get("socket_max_unwritten_bytes")
    flag_registry.set_unchecked("socket_max_unwritten_bytes", 256 * 1024)
    try:
        saw_overcrowded = False
        for _ in range(300):
            rc = sock.write(b"q" * 65536)
            if rc == ErrorCode.EOVERCROWDED:
                saw_overcrowded = True
                break
        assert saw_overcrowded, "write queue never backpressured"
    finally:
        flag_registry.set_unchecked("socket_max_unwritten_bytes", old)
        conn.close()
        lsock.close()
        sock.recycle()


def test_socket_map_dedups():
    acceptor = Acceptor(EndPoint(ip=LOOP, port=0), messenger=InputMessenger())
    smap = SocketMap()
    try:
        s1 = smap.get_or_create(f"{LOOP}:{acceptor.port}")
        s2 = smap.get_or_create(f"{LOOP}:{acceptor.port}")
        assert s1 is s2
        assert len(smap) == 1
    finally:
        smap.recycle_all()
        acceptor.stop()


def test_iobuf_write_zero_copy_path(echo_server):
    """write() accepts an IOBuf directly (the zero-copy path the RPC layer
    uses: pack header bytes + share the payload blocks)."""
    c = _Client(f"{LOOP}:{echo_server.port}")
    try:
        payload = b"P" * 100_000
        data = pack_frame(Meta(service="e", method="e"), payload, 5)
        buf = IOBuf()
        buf.append(data)
        assert c.sock.write(buf) == 0
        frame = c.wait(5)
        assert frame.payload == payload
    finally:
        c.sock.recycle()


class TestOversizedHeaderRejected:
    def test_crafted_giant_header_fails_connection_before_buffering(self):
        # A valid-magic header declaring a ~4GiB body must be rejected at
        # header time on the native fast path (not buffered until OOM).
        import socket as pysocket
        import struct
        import time

        from incubator_brpc_tpu.rpc import Channel, Server

        srv = Server()
        srv.add_service("t", {"echo": lambda cntl, req: req})
        assert srv.start(0)
        try:
            # establish the preferred protocol with one good call first
            ch = Channel()
            assert ch.init(f"127.0.0.1:{srv.port}")
            assert ch.call_method("t", "echo", b"ok").ok()

            c = pysocket.create_connection(("127.0.0.1", srv.port))
            hdr = struct.pack(
                "<8I", 0x54505243, 0xFFFFFF00, 0, 1, 0, 0, 0, 0
            )
            c.sendall(hdr + b"slow-drip")
            c.settimeout(5)
            # server must close the connection (recv -> EOF), not buffer
            deadline = time.monotonic() + 5
            got = b"x"
            while got and time.monotonic() < deadline:
                try:
                    got = c.recv(4096)
                except (ConnectionResetError, OSError):
                    got = b""
            assert not got, "connection not closed after oversized header"
            c.close()
        finally:
            srv.stop()
            srv.join(timeout=5)


def test_drain_inline_slow_reader_and_contenders(echo_server):
    """drain_inline=True (the stream writer's caller-driven KeepWrite):
    the calling thread polls POLLOUT itself past a full kernel buffer, and
    frames queued by contenders while it holds drainer-ship still flush in
    order."""
    c = _Client(f"{LOOP}:{echo_server.port}")
    try:
        # large enough to overrun the loopback sndbuf several times
        big = b"D" * (8 << 20)
        data = pack_frame(Meta(service="e", method="e"), big, 7001)
        contender = pack_frame(Meta(service="e", method="e"), b"tail", 7002)
        rcs = []

        def contend():
            rcs.append(c.sock.write(contender))

        t = threading.Thread(target=contend)
        rc = None

        def drive():
            nonlocal rc
            t.start()  # contender races the inline drainer
            rc = c.sock.write(data, timeout=30, drain_inline=True)

        d = threading.Thread(target=drive)
        d.start()
        d.join(30)
        t.join(10)
        assert rc == 0 and rcs == [0]
        assert c.wait(7001, timeout=30.0).payload == big
        assert c.wait(7002, timeout=30.0).payload == b"tail"
    finally:
        c.sock.recycle()


def test_drain_inline_timeout_falls_back_to_keepwrite(echo_server):
    """When the inline drainer's timeout elapses with bytes still queued,
    it must hand off to the KeepWrite fiber — the frame still arrives."""
    c = _Client(f"{LOOP}:{echo_server.port}")
    try:
        big = b"F" * (8 << 20)
        data = pack_frame(Meta(service="e", method="e"), big, 7003)
        # timeout=0 expires immediately: the poll loop gives up on round one
        rc = c.sock.write(data, timeout=0, drain_inline=True)
        assert rc == 0
        assert c.wait(7003, timeout=30.0).payload == big
    finally:
        c.sock.recycle()


class TestBulkReadEscalation:
    """Saturated-stream drains escalate to big malloc'd blocks
    (append_from_fd_bulk) after consecutive full bursts; the re-cut byte
    stream must stay intact across the pooled->bulk->pooled transitions."""

    def test_large_echo_roundtrip_through_bulk_path(self):
        from incubator_brpc_tpu.rpc import Channel, Controller, Server

        srv = Server()
        srv.add_service("bulk", {"echo": lambda cntl, req: req})
        assert srv.start(0)
        try:
            ch = Channel()
            assert ch.init(f"127.0.0.1:{srv.port}")
            # 16 MiB >> the 512 KiB pooled burst: the server's drain sees
            # many consecutive full reads and escalates; the response
            # drives the client's drain the same way
            blob = bytes(range(256)) * (16 * 4096)
            for _ in range(2):
                cntl = ch.call_method(
                    "bulk", "echo", blob, cntl=Controller(timeout_ms=60000)
                )
                assert cntl.ok(), cntl.error_text
                assert cntl.response_payload == blob
            # and small frames still flow after de-escalation
            c = ch.call_method("bulk", "echo", b"tiny")
            assert c.ok() and c.response_payload == b"tiny"
        finally:
            srv.stop()
            srv.join(timeout=10)

    def test_bulk_append_iobuf_api(self):
        import os
        import socket as pysock

        from incubator_brpc_tpu.iobuf import IOBuf

        import threading

        a, b = pysock.socketpair()
        try:
            payload = os.urandom(1 << 20)
            # writer thread: sendall past the socketpair buffer would
            # deadlock against an unread peer
            w = threading.Thread(target=a.sendall, args=(payload,))
            w.start()
            buf = IOBuf()
            got = 0
            while got < len(payload):
                rc = buf.append_from_fd_bulk(
                    b.fileno(), 4 << 20, 256 << 10
                )
                assert rc > 0, rc
                got += rc
            w.join(timeout=10)
            assert buf.to_bytes() == payload
        finally:
            a.close()
            b.close()
