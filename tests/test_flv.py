"""FLV muxer/demuxer (protocol/flv.py — reference rtmp.h:388-440
FlvWriter/FlvReader): spec-worked header/tag bytes, round trips,
incremental demux, corruption handling, and the RTMP publish → FLV dump
integration on a live server.
"""

from __future__ import annotations

import io
import struct
import time

import pytest

from incubator_brpc_tpu.protocol import amf0, flv, rtmp
from incubator_brpc_tpu.protocol.tbus_std import ParseError
from incubator_brpc_tpu.rpc import Server, ServerOptions


class TestWire:
    def test_header_fixture(self):
        # "FLV" 0x01 flags u32be(9) — audio+video = 0x05
        assert flv.pack_header() == b"FLV\x01\x05\x00\x00\x00\x09"
        assert flv.pack_header(audio=False) == b"FLV\x01\x01\x00\x00\x00\x09"

    def test_tag_fixture(self):
        # audio tag, ts=0x12345678 (extension byte carries bits 24-31)
        tag = flv.pack_tag(flv.TAG_AUDIO, 0x12345678, b"AB")
        assert tag[0] == 8
        assert tag[1:4] == b"\x00\x00\x02"            # size 2
        assert tag[4:7] == b"\x34\x56\x78"            # ts low 24
        assert tag[7] == 0x12                          # ts ext
        assert tag[8:11] == b"\x00\x00\x00"            # stream id
        assert tag[11:13] == b"AB"
        assert tag[13:17] == struct.pack(">I", 13)     # prev tag size

    def test_oversized_tag_rejected(self):
        with pytest.raises(ValueError):
            flv.pack_tag(flv.TAG_VIDEO, 0, b"\x00" * (0xFFFFFF + 1))


class TestRoundTrip:
    def test_write_read(self):
        out = io.BytesIO()
        w = flv.FlvWriter(out)
        meta = amf0.encode_all("onMetaData", {"duration": 0.0})
        w.write_script(0, meta)
        w.write_audio(10, b"\xaf\x01AAA")
        w.write_video(20, b"\x17\x01VVV")
        r = flv.FlvReader(out.getvalue())
        tags = list(r)
        assert [t[0] for t in tags] == [
            flv.TAG_SCRIPT, flv.TAG_AUDIO, flv.TAG_VIDEO
        ]
        assert tags[1] == (flv.TAG_AUDIO, 10, b"\xaf\x01AAA")
        assert tags[2] == (flv.TAG_VIDEO, 20, b"\x17\x01VVV")
        assert amf0.decode_all(tags[0][2])[0] == "onMetaData"

    def test_incremental_feed(self):
        out = io.BytesIO()
        w = flv.FlvWriter(out)
        w.write_audio(1, b"x" * 100)
        w.write_video(2, b"y" * 200)
        wire = out.getvalue()
        r = flv.FlvReader()
        got = []
        for i in range(0, len(wire), 7):
            r.feed(wire[i : i + 7])
            got.extend(iter(r))
        assert [(t, ts, len(d)) for t, ts, d in got] == [
            (flv.TAG_AUDIO, 1, 100), (flv.TAG_VIDEO, 2, 200)
        ]

    def test_extended_timestamp_roundtrip(self):
        out = io.BytesIO()
        w = flv.FlvWriter(out)
        w.write_video(0x7FABCDEF, b"v")
        tags = list(flv.FlvReader(out.getvalue()))
        assert tags[0][1] == 0x7FABCDEF

    def test_bad_signature_raises(self):
        r = flv.FlvReader(b"NOT-AN-FLV-FILE-AT-ALL")
        with pytest.raises(ParseError):
            r.next_tag()

    def test_corrupt_prev_tag_size_raises(self):
        out = io.BytesIO()
        w = flv.FlvWriter(out)
        w.write_audio(0, b"a")
        wire = bytearray(out.getvalue())
        wire[-1] ^= 0xFF  # corrupt the trailing previous_tag_size
        r = flv.FlvReader(bytes(wire))
        with pytest.raises(ParseError):
            r.next_tag()

    def test_rtmp_message_tee(self):
        out = io.BytesIO()
        w = flv.FlvWriter(out)
        assert w.write_message(
            rtmp.RtmpMessage(rtmp.MSG_AUDIO, 5, 1, b"aud")
        )
        assert not w.write_message(
            rtmp.RtmpMessage(rtmp.MSG_COMMAND_AMF0, 0, 0, b"cmd")
        )
        tags = list(flv.FlvReader(out.getvalue()))
        assert tags == [(flv.TAG_AUDIO, 5, b"aud")]


class TestRtmpDumpIntegration:
    def test_player_close_does_not_destroy_publisher_dump(self):
        # a subscriber leaving must not pop the publisher's writer (the
        # dump would restart with a second FLV header mid-stream)
        sinks = []

        def sink_factory(name):
            sinks.append(io.BytesIO())
            return sinks[-1]

        srv = Server(
            ServerOptions(
                usercode_inline=True,
                rtmp_service=flv.FlvDumpService(sink_factory),
            )
        )
        srv.add_service("svc", {"echo": lambda cntl, req: req})
        assert srv.start(0)
        try:
            pub = rtmp.RtmpClient("127.0.0.1", srv.port)
            ps = pub.create_stream()
            assert ps.publish("cam2")
            ps.send_audio(0, b"\xaf\x01a1")

            sub = rtmp.RtmpClient("127.0.0.1", srv.port)
            ss = sub.create_stream()
            assert ss.play("cam2")
            ss.close()  # deleteStream from the PLAYER
            sub.close()
            time.sleep(0.3)  # let the server process the player's close

            ps.send_audio(40, b"\xaf\x01a2")  # publisher keeps going
            deadline = time.monotonic() + 10
            tags = []
            while time.monotonic() < deadline:
                if sinks and not sinks[0].closed:
                    tags = list(flv.FlvReader(sinks[0].getvalue()))
                    if len(tags) >= 2:
                        break
                time.sleep(0.05)
            assert len(sinks) == 1, "dump restarted into a second sink"
            assert [d for t, ts, d in tags if t == flv.TAG_AUDIO] == [
                b"\xaf\x01a1", b"\xaf\x01a2"
            ]
            pub.close()
        finally:
            srv.stop()

    def test_published_stream_dumps_to_flv(self):
        sinks = {}

        def sink_factory(name):
            sinks[name] = io.BytesIO()
            return sinks[name]

        srv = Server(
            ServerOptions(
                usercode_inline=True,
                rtmp_service=flv.FlvDumpService(sink_factory),
            )
        )
        srv.add_service("svc", {"echo": lambda cntl, req: req})
        assert srv.start(0)
        try:
            pub = rtmp.RtmpClient("127.0.0.1", srv.port)
            ps = pub.create_stream()
            assert ps.publish("cam1")
            ps.send_metadata({"width": 320.0})
            ps.send_audio(0, b"\xaf\x00HDR")
            ps.send_video(40, b"\x17\x01FRM")
            deadline = time.monotonic() + 10
            kinds: list = []
            tags: list = []
            while time.monotonic() < deadline:
                buf = sinks.get("cam1")
                if buf is not None:
                    tags = list(flv.FlvReader(buf.getvalue()))
                    kinds = [t for t, _, _ in tags]
                    if {flv.TAG_SCRIPT, flv.TAG_AUDIO, flv.TAG_VIDEO} <= set(
                        kinds
                    ):
                        break
                time.sleep(0.05)
            pub.close()
            assert flv.TAG_SCRIPT in kinds
            assert flv.TAG_AUDIO in kinds and flv.TAG_VIDEO in kinds
            script = next(d for t, _, d in tags if t == flv.TAG_SCRIPT)
            name, meta = amf0.decode_all(script)
            assert name == "onMetaData" and meta["width"] == 320.0
        finally:
            srv.stop()
