"""Multi-controller device plane: two real processes, one link.

The single-controller suite (test_device_link.py) proves the link
machinery with both halves in one process. These tests prove the
DEPLOYMENT the reference transport actually ships: two processes
(jax.distributed over a 2-device global CPU mesh), handshake + control
plane over a real TCP socket between them, RPC frames over lockstep SPMD
exchange steps — the rdma_endpoint.h:42-213 shape (handshake between real
peers) with per-host device init (rdma_helper.cpp).
"""

from __future__ import annotations

import pytest

from incubator_brpc_tpu.transport.mc_worker import orchestrate_pair

# jaxlib refuses multi-process computations on some backends (CPU builds
# without cross-host collectives raise this in every worker): when the
# probe run dies on it, every pairing in this module would burn its full
# handshake deadline the same way — skip them fast instead
_FABRIC_UNSUPPORTED = "Multiprocess computations aren't implemented"


@pytest.fixture(scope="module")
def fabric_pair():
    """One two-process run, shared by the module: its stats back
    test_two_process_echo, and its failure mode gates everything else —
    a backend that cannot run multi-process computations at all fails
    each orchestration only after minutes of deadline. The cheap psum
    probe (seconds) fronts the full pair so unsupported environments
    skip before ANY doomed handshake burns its deadline."""
    from incubator_brpc_tpu.transport.mc_worker import multiprocess_capable

    if not multiprocess_capable():
        pytest.skip(f"jax backend: {_FABRIC_UNSUPPORTED}")
    try:
        return orchestrate_pair()
    except AssertionError as e:
        if _FABRIC_UNSUPPORTED in str(e):
            pytest.skip(f"jax backend: {_FABRIC_UNSUPPORTED}")
        raise


def test_two_process_echo(fabric_pair):
    """RPCs echo across processes over the device plane; the cross-host
    wire acks advance; the close dance quiesces both sides cleanly."""
    stats, _, _ = fabric_pair
    assert stats["n_rpcs"] == 8
    assert stats["peer_ack"] > 0
    assert stats["steps"] >= stats["n_rpcs"]
    assert stats["final_target"] is not None
    # two DISTINCT global devices — one per process
    assert len(set(stats["devices"])) == 2


def test_two_process_windowed_burst(fabric_pair):
    """Payloads spanning many slots under a small window: the lockstep
    credit (own undrained completions) must pipeline without deadlock and
    without corrupting the re-cut byte stream."""
    stats, _, _ = orchestrate_pair(
        extra=(
            "--n-rpcs", "4",
            "--payload", "20000",
            "--slot-words", "128",
            "--window", "2",
        )
    )
    # 20000-byte echoes through 512-byte slots: many steps per RPC
    assert stats["steps"] > 40 * 4
    assert stats["peer_ack"] > 0


def test_three_process_fabric(fabric_pair):
    """Client + TWO server processes in one jax.distributed group: a
    PartitionChannel fans each call over two cross-process device links —
    the client device holds a star of lockstep sub-meshes (the N-party
    fabric spanning real hosts)."""
    from incubator_brpc_tpu.transport.mc_worker import orchestrate_fabric

    stats, _ = orchestrate_fabric(n_servers=2, extra=("--n-rpcs", "4"))
    assert len(stats["links"]) == 2
    # one client device shared, two distinct server devices
    assert len({l["devices"][0] for l in stats["links"]}) == 1
    assert len({l["devices"][1] for l in stats["links"]}) == 2
    assert all(l["peer_ack"] > 0 for l in stats["links"])


def test_peer_death_fails_link_fast(fabric_pair):
    """A server process that vanishes mid-traffic (os._exit in a handler,
    no goodbye on any plane) must fail the client's link promptly — via
    the host socket under the control stream, not a wedge timeout — and
    the in-flight RPC errors instead of hanging."""
    from incubator_brpc_tpu.transport.mc_worker import orchestrate_peer_death

    stats, transcript = orchestrate_peer_death(die_after=3)
    # the client's connection warm-up consumes one server-side echo
    assert stats["ok_before_death"] >= 2, transcript
    assert stats["failed_at"] >= 2
    # fast failure: EFAILEDSOCKET via the dying TCP socket under the
    # control stream — NOT the 30 s RPC deadline, NOT the wedge timer
    from incubator_brpc_tpu.utils.status import ErrorCode

    assert stats["error_code"] == ErrorCode.EFAILEDSOCKET, stats
    assert "SERVER_DYING" in transcript


def test_three_process_collective_session(fabric_pair):
    """The pipelined cross-process collective: scheduled once over the
    host plane, K lockstep pmean steps across three processes' devices
    with operands resident on-device through the chain. Every party must
    converge to the global mean (each verifies independently)."""
    from incubator_brpc_tpu.transport.mc_worker import orchestrate_fabric

    stats, transcript = orchestrate_fabric(
        n_servers=2, extra=("--n-rpcs", "2", "--collective-steps", "32")
    )
    coll = stats["collective"]
    assert coll is not None, transcript
    assert coll["parties"] == 3
    assert coll["steps"] == 32
    # amortization: a per-step cost in the low milliseconds on the CPU
    # mesh — orders below the per-RPC host round trip it replaces
    assert coll["per_step_ms"] < 250, coll
