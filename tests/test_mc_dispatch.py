"""Collective method plane (parallel/mc_dispatch.py): ANY registered
device method runs a pipelined N-party session with fingerprint
validation — pmean is just one registered method on the plane.

Two tiers:
- in-process tests on the virtual 8-device mesh (single controller, every
  party device addressable): the proposal/accept/run/close machinery, the
  fingerprint reject, the convergent N-party step join, and the
  byte-identity contract against the single-controller fused dispatch;
- subprocess tests (real jax.distributed processes, the deployment the
  plane exists for), gated by the same fast capability probe as
  tests/test_mc_link.py.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from incubator_brpc_tpu.transport.mc_worker import (
    SESSION_WIDTH,
    _scale_psum_kernel,
    session_expected,
)

_FABRIC_UNSUPPORTED = "Multiprocess computations aren't implemented"


@pytest.fixture(scope="module")
def shard_map_capable():
    """In-process sessions dispatch shard_map over the virtual mesh; skip
    the module in one cheap step where this jax cannot trace it at all
    (the test_parallel.py probe pattern, via the compat seam)."""
    import jax

    from incubator_brpc_tpu.parallel.compat import resolve_shard_map

    try:
        resolve_shard_map()
    except ImportError:
        pytest.skip("no shard_map in this jax build")
    if len(jax.devices()) < 4:
        pytest.skip("needs a 4+ device mesh")
    return True


@pytest.fixture
def registered_scale(shard_map_capable):
    """("dsvc", "scale") bound to the psum+elementwise kernel in THIS
    process's registry (proposer and in-process servers share it)."""
    from incubator_brpc_tpu.rpc.device_method import (
        DeviceMethod,
        register_device_method,
        lookup_device_method,
    )

    dm = DeviceMethod(_scale_psum_kernel, width=SESSION_WIDTH)
    prev = lookup_device_method("dsvc", "scale")
    register_device_method("dsvc", "scale", dm)
    yield dm
    if prev is not None:
        register_device_method("dsvc", "scale", prev)


def _collective_servers(n, width=SESSION_WIDTH, kernel=_scale_psum_kernel):
    """n servers on distinct mesh devices, each registering the kernel as
    a device method AND serving the collective method plane."""
    from incubator_brpc_tpu.rpc import Server, ServerOptions, device_method

    servers = []
    for i in range(n):
        s = Server(
            ServerOptions(
                device_index=i + 1,
                usercode_inline=True,
                enable_collective_service=True,
                collective_max_concurrency=0,
            )
        )
        s.add_service("dsvc", {"scale": device_method(kernel, width=width)})
        assert s.start(0)
        servers.append(s)
    return servers


def _host_channels(servers):
    from incubator_brpc_tpu.rpc import Channel

    chans = []
    for s in servers:
        ch = Channel()
        assert ch.init(f"127.0.0.1:{s.port}")
        chans.append(ch)
    return chans


class TestProposalValidation:
    """Accept-phase admission: the clean control-stream reject."""

    def _proposal(self, dm, parties, **over):
        d = {
            "parties": parties,
            "index": 1,
            "steps": 2,
            "width": dm.width,
            "service": "dsvc",
            "method": "scale",
            "fingerprint": dm.fingerprint(),
            "phase": "accept",
        }
        d.update(over)
        return json.dumps(d).encode()

    def test_accept_validates_fingerprint(self, registered_scale):
        import jax

        from incubator_brpc_tpu.rpc import Controller
        from incubator_brpc_tpu.utils.status import ErrorCode

        dm = registered_scale
        parties = [d.id for d in jax.devices()[:3]]
        servers = _collective_servers(1)
        try:
            (ch,) = _host_channels(servers)

            ok = ch.call_method(
                "_tpu_transport", "collective_dispatch",
                self._proposal(dm, parties),
                cntl=Controller(timeout_ms=30000),
            )
            assert ok.ok(), ok.error_text
            ack = json.loads(ok.response_payload.decode())
            assert ack["accept"] is True and ack["target"] == 2

            # same name, different fingerprint -> clean reject, no lockstep
            bad = ch.call_method(
                "_tpu_transport", "collective_dispatch",
                self._proposal(dm, parties, fingerprint="deadbeef00000000"),
                cntl=Controller(timeout_ms=30000),
            )
            assert bad.failed()
            assert bad.error_code == ErrorCode.EREQUEST
            assert "fingerprint mismatch" in bad.error_text

            # unknown method name -> ENOMETHOD
            miss = ch.call_method(
                "_tpu_transport", "collective_dispatch",
                self._proposal(dm, parties, method="nosuch"),
                cntl=Controller(timeout_ms=30000),
            )
            assert miss.failed()
            assert miss.error_code == ErrorCode.ENOMETHOD

            # geometry mismatch (width disagrees with the registration)
            geo = ch.call_method(
                "_tpu_transport", "collective_dispatch",
                self._proposal(dm, parties, width=dm.width * 2),
                cntl=Controller(timeout_ms=30000),
            )
            assert geo.failed()

            # out-of-bounds proposal
            oob = ch.call_method(
                "_tpu_transport", "collective_dispatch",
                self._proposal(dm, parties, steps=0),
                cntl=Controller(timeout_ms=30000),
            )
            assert oob.failed()
            assert oob.error_code == ErrorCode.EREQUEST
        finally:
            for s in servers:
                s.stop()
                s.join(timeout=5)

    def test_run_phase_enforces_accept_floor(
        self, registered_scale, tuned_flags
    ):
        """A run proposal below this party's accepted step floor means
        the proposer never folded our accept target — clean reject, not
        a silent dispatch of an un-agreed count (what keeps the phase-3
        close-barrier echo meaningful)."""
        import jax

        from incubator_brpc_tpu.rpc import Controller
        from incubator_brpc_tpu.utils.status import ErrorCode

        tuned_flags("mc_dispatch_min_steps", 6)
        dm = registered_scale
        parties = [d.id for d in jax.devices()[:2]]
        servers = _collective_servers(1)
        try:
            (ch,) = _host_channels(servers)
            low = ch.call_method(
                "_tpu_transport", "collective_dispatch",
                self._proposal(dm, parties, steps=2, phase=None),
                cntl=Controller(timeout_ms=30000),
            )
            assert low.failed()
            assert low.error_code == ErrorCode.EREQUEST
            assert "floor" in low.error_text
        finally:
            for s in servers:
                s.stop()
                s.join(timeout=5)

    def test_reject_counter_advances(self, registered_scale):
        import jax

        from incubator_brpc_tpu.parallel.mc_dispatch import dispatch_rejects
        from incubator_brpc_tpu.rpc import Controller

        dm = registered_scale
        parties = [d.id for d in jax.devices()[:2]]
        servers = _collective_servers(1)
        try:
            (ch,) = _host_channels(servers)
            before = dispatch_rejects.get_value()
            bad = ch.call_method(
                "_tpu_transport", "collective_dispatch",
                self._proposal(dm, parties, fingerprint="0" * 16),
                cntl=Controller(timeout_ms=30000),
            )
            assert bad.failed()
            assert dispatch_rejects.get_value() == before + 1
        finally:
            for s in servers:
                s.stop()
                s.join(timeout=5)


class TestInProcessSessions:
    """The scheduler machinery with every party addressable (single
    controller): proposal fan-out, accept barrier, run barrier, merge."""

    def test_user_kernel_session_matches_integer_model(
        self, registered_scale
    ):
        import jax

        from incubator_brpc_tpu.parallel.mc_dispatch import propose_dispatch

        servers = _collective_servers(2)
        try:
            chans = _host_channels(servers)
            party_ids = [jax.devices()[1].id, jax.devices()[2].id]
            operands = [bytes(range(40)), bytes(range(100, 180))]
            out = propose_dispatch(
                chans, party_ids, "dsvc", "scale", operands,
                steps=3, proposer_index=None, timeout_ms=60000,
            )
            assert out["final_steps"] == 3
            assert out["results"] == session_expected(operands, 3)
        finally:
            for s in servers:
                s.stop()
                s.join(timeout=5)

    def test_party_spans_carry_proposer_trace(
        self, registered_scale, tuned_flags
    ):
        """ISSUE 15 acceptance: every party's collective session span
        carries the PROPOSER's trace id — the session proposal stamps
        the fleet trace context on its control RPCs, and each party
        parents its spans into it (forced by the sampled bit, so no
        party drops out to a dry local bucket)."""
        import jax

        from incubator_brpc_tpu.builtin.rpcz import span_store
        from incubator_brpc_tpu.parallel.mc_dispatch import propose_dispatch

        tuned_flags("enable_rpcz", True)
        span_store.clear()
        servers = _collective_servers(2)
        try:
            chans = _host_channels(servers)
            party_ids = [jax.devices()[1].id, jax.devices()[2].id]
            operands = [bytes(range(40)), bytes(range(100, 180))]
            out = propose_dispatch(
                chans, party_ids, "dsvc", "scale", operands,
                steps=2, proposer_index=None, timeout_ms=60000,
            )
            assert out["final_steps"] == 2
        finally:
            for s in servers:
                s.stop()
                s.join(timeout=5)
        collective = [
            sp
            for sp in span_store.recent(limit=1000)
            if sp.span_type == "collective"
        ]
        # one session span per party (both servers are in-process, so
        # the shared store holds every party's)
        assert len(collective) >= 2
        traces = {sp.trace_id for sp in collective}
        assert len(traces) == 1 and 0 not in traces, (
            f"party session spans scattered across traces: {traces}"
        )
        # and the parties' handler (server) spans joined the same trace
        servers_spans = [
            sp
            for sp in span_store.by_trace(traces.pop())
            if sp.span_type == "server"
        ]
        assert len(servers_spans) >= 2
        span_store.clear()

    def test_nparty_close_converges_on_max_target(
        self, registered_scale, tuned_flags
    ):
        """One party demands a deeper pipeline (mc_dispatch_min_steps):
        its accept raises the target, the proposer folds max over ALL
        targets, and every party dispatches exactly the raised count —
        the 2-party close dance's monotone join at N parties."""
        import jax

        from incubator_brpc_tpu.parallel.mc_dispatch import propose_dispatch

        tuned_flags("mc_dispatch_min_steps", 5)
        servers = _collective_servers(3)
        try:
            chans = _host_channels(servers)
            party_ids = [d.id for d in jax.devices()[1:4]]
            operands = [b"a" * 10, b"b" * 20, b"c" * 30]
            out = propose_dispatch(
                chans, party_ids, "dsvc", "scale", operands,
                steps=2, proposer_index=None, timeout_ms=60000,
            )
            # proposed 2, every accept answered max(2, 5) = 5
            assert out["final_steps"] == 5
            assert out["results"] == session_expected(operands, 5)
        finally:
            for s in servers:
                s.stop()
                s.join(timeout=5)

    def test_byte_identical_with_single_controller_fused_dispatch(
        self, registered_scale
    ):
        """The contract that makes the two planes ONE API: the same
        kernel, same axis name, same party order — the session's merged
        bytes equal the single-controller fused dispatch's merge."""
        import jax

        from incubator_brpc_tpu.parallel.mc_dispatch import propose_dispatch
        from incubator_brpc_tpu.rpc import Channel, ChannelOptions, Controller
        from incubator_brpc_tpu.rpc.combo import ParallelChannel, SubCall

        operands = [bytes([i * 3]) * (20 + i) for i in range(3)]

        class PerIndexMapper:
            def map(self, i, nchan, service, method, request):
                return SubCall(request=operands[i])

        servers = _collective_servers(3)
        try:
            pc = ParallelChannel(fuse_device_calls=True)
            for s in servers:
                ch = Channel()
                assert ch.init(
                    f"127.0.0.1:{s.port}",
                    options=ChannelOptions(transport="tpu", timeout_ms=60000),
                )
                pc.add_channel(ch, call_mapper=PerIndexMapper())
            fused = pc.call_method(
                "dsvc", "scale", b"ignored", cntl=Controller(timeout_ms=60000)
            )
            assert fused.ok(), fused.error_text
            assert getattr(fused, "collective_fused", False), (
                "single-controller fused path not taken"
            )

            chans = _host_channels(servers)
            party_ids = [d.id for d in jax.devices()[1:4]]
            out = propose_dispatch(
                chans, party_ids, "dsvc", "scale", operands,
                steps=1, proposer_index=None, timeout_ms=60000,
            )
            assert b"".join(out["results"]) == fused.response_payload
            assert fused.response_payload == b"".join(
                session_expected(operands, 1)
            )
        finally:
            for s in servers:
                s.stop()
                s.join(timeout=5)

    def test_proposer_as_party_and_per_kernel_counters(
        self, registered_scale
    ):
        """The proposer runs its own chain when it owns a party device;
        plane + per-kernel bvars advance."""
        import jax

        from incubator_brpc_tpu.parallel.mc_dispatch import (
            _method_counter,
            dispatch_sessions,
            propose_dispatch,
        )

        sessions_before = dispatch_sessions.get_value()
        kernel_before = _method_counter("dsvc", "scale").get_value()
        servers = _collective_servers(1)
        try:
            chans = _host_channels(servers)
            # proposer plays party 0 on device 0; the server plays party 1
            party_ids = [jax.devices()[0].id, jax.devices()[1].id]
            operands = [b"proposer-side", b"server-side!!"]
            out = propose_dispatch(
                chans, party_ids, "dsvc", "scale", operands,
                steps=2, proposer_index=0, timeout_ms=60000,
            )
            assert out["elapsed_s"] is not None
            assert out["results"] == session_expected(operands, 2)
        finally:
            for s in servers:
                s.stop()
                s.join(timeout=5)
        # proposer + server each ran one session
        assert dispatch_sessions.get_value() >= sessions_before + 2
        assert _method_counter("dsvc", "scale").get_value() >= kernel_before + 2

    def test_pmean_is_just_one_registered_method(self, shard_map_capable):
        """mc_collective rides the plane: its resolver mints the pmean
        method per width, and run_collective_session converges to the
        global mean through mc_dispatch.run_dispatch_session."""
        import jax

        from incubator_brpc_tpu.parallel import mc_dispatch
        from incubator_brpc_tpu.parallel.mc_collective import (
            PMEAN_METHOD,
            PMEAN_SERVICE,
            expected_mean,
            run_collective_session,
        )

        width = 32
        dm = mc_dispatch.resolve_method(PMEAN_SERVICE, PMEAN_METHOD, 4 * width)
        assert dm is not None and dm.width == 4 * width
        # the resolver is deterministic: same width -> same fingerprint
        dm2 = mc_dispatch.resolve_method(PMEAN_SERVICE, PMEAN_METHOD, 4 * width)
        assert dm2.fingerprint() == dm.fingerprint()

        party_ids = [d.id for d in jax.devices()[:4]]
        own, elapsed = run_collective_session(
            party_ids, own_index=2, steps=1, width=width, seed=11
        )
        np.testing.assert_allclose(
            own, expected_mean(11, len(party_ids), width), atol=1e-5
        )

    def test_span_carries_method_identity(
        self, registered_scale, tuned_flags
    ):
        """rpcz spans on the plane name the kernel they ran."""
        import jax

        from incubator_brpc_tpu.builtin.rpcz import span_store
        from incubator_brpc_tpu.parallel.mc_dispatch import propose_dispatch

        tuned_flags("enable_rpcz", True)
        span_store.clear()
        servers = _collective_servers(1)
        try:
            chans = _host_channels(servers)
            party_ids = [jax.devices()[0].id, jax.devices()[1].id]
            propose_dispatch(
                chans, party_ids, "dsvc", "scale", [b"x" * 8, b"y" * 8],
                steps=1, proposer_index=0, timeout_ms=60000,
            )
            spans = [
                s
                for s in span_store.recent(limit=500)
                if s.span_type == "collective"
            ]
            assert spans, "no collective span sampled"
            notes = " ".join(
                text for s in spans for _, text in s.annotations
            )
            assert "method=dsvc.scale" in notes
            assert "fingerprint=" in notes
        finally:
            span_store.clear()
            for s in servers:
                s.stop()
                s.join(timeout=5)


class TestMcLoweringRouting:
    """ParallelChannel's plane choice, isolated from real links: stub
    sockets whose links look multi-controller (own_side set) must route
    the call into mc_dispatch.lower_parallel_call; mixed planes and a
    failing lowering must fall back to the host fan-out silently."""

    class _FakeLink:
        def __init__(self, dev, mc=True):
            self._mesh = object()
            self.devices = [None, dev]
            if mc:
                self.own_side = 0

    class _FakeSock:
        def __init__(self, link, fp_map):
            self.link = link
            self.device_methods = fp_map

    class _FakeChannel:
        def __init__(self, ds):
            class _O:
                transport = "tpu"

            self._options = _O()
            self._lb = None
            self._ds = ds
            self.host_calls = 0

        def _pick_socket(self, cntl):
            return self._ds

        def call_method(self, service, method, request, cntl=None, done=None):
            self.host_calls += 1
            cntl.response_payload = b"host:" + request
            if done:
                done(cntl)
            return cntl

    class _Dev:
        def __init__(self, i):
            self.id = i

    def _pc(self, registered_scale, mc_flags):
        from incubator_brpc_tpu.rpc.combo import ParallelChannel

        pc = ParallelChannel(fuse_device_calls=True)
        for i, mc in enumerate(mc_flags):
            link = self._FakeLink(self._Dev(100 + i), mc=mc)
            ds = self._FakeSock(
                link, {"dsvc.scale": registered_scale.fingerprint()}
            )
            pc.add_channel(self._FakeChannel(ds))
        return pc

    def test_all_mc_links_route_to_method_plane(
        self, registered_scale, monkeypatch
    ):
        from incubator_brpc_tpu.parallel import mc_dispatch
        from incubator_brpc_tpu.rpc import Controller

        seen = {}

        def fake_lower(channels, devices, service, method, requests, timeout_ms):
            seen["devices"] = [d.id for d in devices]
            seen["requests"] = list(requests)
            seen["pair"] = (service, method)
            return [b"mc:" + r for r in requests]

        monkeypatch.setattr(mc_dispatch, "lower_parallel_call", fake_lower)
        pc = self._pc(registered_scale, [True, True])
        cntl = pc.call_method(
            "dsvc", "scale", b"req", cntl=Controller(timeout_ms=5000)
        )
        assert cntl.ok(), cntl.error_text
        assert getattr(cntl, "collective_fused", False) is True
        # merged in channel-index order from the per-party session results
        assert cntl.response_payload == b"mc:reqmc:req"
        assert seen["pair"] == ("dsvc", "scale")
        assert seen["devices"] == [100, 101]
        assert all(ch.host_calls == 0 for ch, _m, _r in pc._subs)

    def test_mixed_planes_fall_back_to_host(
        self, registered_scale, monkeypatch
    ):
        from incubator_brpc_tpu.parallel import mc_dispatch
        from incubator_brpc_tpu.rpc import Controller

        def boom(*a, **kw):  # the lowering must not even be attempted
            raise AssertionError("mixed planes must not lower")

        monkeypatch.setattr(mc_dispatch, "lower_parallel_call", boom)
        pc = self._pc(registered_scale, [True, False])
        cntl = pc.call_method(
            "dsvc", "scale", b"req", cntl=Controller(timeout_ms=5000)
        )
        assert cntl.ok(), cntl.error_text
        assert getattr(cntl, "collective_fused", False) is False
        assert cntl.response_payload == b"host:reqhost:req"

    def test_failed_lowering_falls_back_to_host(
        self, registered_scale, monkeypatch
    ):
        from incubator_brpc_tpu.parallel import mc_dispatch
        from incubator_brpc_tpu.rpc import Controller

        def fail_lower(*a, **kw):
            raise RuntimeError("peer rejected")

        monkeypatch.setattr(mc_dispatch, "lower_parallel_call", fail_lower)
        pc = self._pc(registered_scale, [True, True])
        cntl = pc.call_method(
            "dsvc", "scale", b"req", cntl=Controller(timeout_ms=5000)
        )
        assert cntl.ok(), cntl.error_text
        assert getattr(cntl, "collective_fused", False) is False
        assert cntl.response_payload == b"host:reqhost:req"


# -- the real deployment: separate OS processes --------------------------------


@pytest.fixture(scope="module")
def fabric_capable():
    """Fast capability probe: one tiny 2-process psum (seconds on a
    backend that refuses multi-process computations) decides whether the
    real-subprocess tier can run at all — no doomed full orchestrations
    burning their handshake deadlines."""
    from incubator_brpc_tpu.transport.mc_worker import multiprocess_capable

    if not multiprocess_capable():
        pytest.skip(f"jax backend: {_FABRIC_UNSUPPORTED}")
    return True


def test_three_process_user_kernel_session(fabric_capable):
    """The tentpole end to end: a user-registered device method (psum +
    elementwise — NOT pmean) pipelines a multi-step session across three
    real processes, fingerprint-validated, every party's bytes matching
    the exact integer model (= the single-controller fused dispatch's
    math, asserted bitwise in TestInProcessSessions)."""
    from incubator_brpc_tpu.transport.mc_worker import orchestrate_session

    stats, transcript = orchestrate_session(n_parties=3, steps=4)
    assert stats["parties"] == 3, transcript
    assert stats["steps"] == 4
    assert stats["method"] == "dsvc.scale"
    assert stats["per_step_ms"] < 250, stats


def test_fingerprint_mismatch_rejects_cleanly(fabric_capable):
    """One process registered a same-name/different-body kernel: the
    accept phase must reject before ANY party enters lockstep (a clean
    RuntimeError on the proposer, no wedge, workers exit 0)."""
    from incubator_brpc_tpu.transport.mc_worker import orchestrate_session

    stats, transcript = orchestrate_session(
        n_parties=3, steps=4, wrong_kernel=True
    )
    assert stats.get("rejected") is True, transcript


def test_parallel_channel_lowers_through_mc_plane(fabric_capable):
    """ParallelChannel over multi-controller links: the fused path cannot
    single-dispatch across controllers, so it schedules a 1-step session
    on the method plane — one API, transport picks the lowering."""
    from incubator_brpc_tpu.transport.mc_worker import orchestrate_fabric

    stats, transcript = orchestrate_fabric(
        n_servers=2, extra=("--n-rpcs", "2", "--mc-lowering-check")
    )
    assert stats["mc_lowered"] is not None, transcript
    assert stats["mc_lowered"]["parties"] == 2


@pytest.mark.slow
def test_eight_party_session(fabric_capable):
    """Fabric scale: 8 real processes, one pipelined session of the user
    kernel (the dryrun_multichip collective_8proc gate, runnable
    standalone)."""
    from incubator_brpc_tpu.transport.mc_worker import orchestrate_session

    stats, transcript = orchestrate_session(n_parties=8, steps=8, timeout=420)
    assert stats["parties"] == 8, transcript
    assert stats["steps"] >= 8
    assert stats["per_step_ms"] < 500, stats


@pytest.mark.slow
def test_chaos_kill_at_step_resumes(fabric_capable):
    """The scriptable chaos drill (the dryrun_multichip chaos_resume
    gate): one REAL party process loses its RPC server at exactly step K;
    the session heals with the spare party and the merged result stays
    byte-identical to the undisturbed model."""
    from incubator_brpc_tpu.transport.mc_worker import (
        orchestrate_chaos_session,
    )

    stats, transcript = orchestrate_chaos_session(
        n_parties=3, steps=8, kill_at=3, checkpoint_every=2, timeout=420
    )
    assert stats["byte_identical"], transcript
    assert stats["replaced_party_ids"], transcript
    # resumed_from is an int when the dead slot's checkpoint was
    # resharable, None when no reachable ring covered it (a true
    # multi-controller fabric) — the heal itself is the gate
