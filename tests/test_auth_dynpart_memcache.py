"""Authenticator + DynamicPartitionChannel + memcache client tests
(reference authenticator.h contract, brpc_partition_channel_unittest.cpp
DynamicPartitionChannel cases, brpc_memcache_unittest.cpp)."""

import threading

import pytest

from incubator_brpc_tpu.protocol import memcache
from incubator_brpc_tpu.rpc import (
    Channel,
    ChannelOptions,
    DynamicPartitionChannel,
    Server,
    ServerOptions,
    SharedSecretAuthenticator,
)
from incubator_brpc_tpu.utils.status import ErrorCode


class TestAuth:
    def _server(self, auth):
        s = Server(options=ServerOptions(auth=auth))
        s.add_service("a", {"echo": lambda c, r: r})
        assert s.start(0)
        return s

    def test_valid_credential_accepted_once_per_connection(self):
        auth = SharedSecretAuthenticator("s3cret")
        s = self._server(auth)
        try:
            ch = Channel()
            assert ch.init(
                f"127.0.0.1:{s.port}", options=ChannelOptions(auth=auth)
            )
            for i in range(3):  # later calls ride the authenticated conn
                cntl = ch.call_method("a", "echo", b"x%d" % i)
                assert cntl.ok(), cntl.error_text
        finally:
            s.stop()

    def test_auth_channels_do_not_share_connections(self):
        """SocketMapKey carries auth (socket_map.h:35): an unauthenticated
        channel to the same endpoint must not ride an authenticated
        connection."""
        auth = SharedSecretAuthenticator("s3cret")
        s = self._server(auth)
        try:
            good = Channel()
            assert good.init(
                f"127.0.0.1:{s.port}", options=ChannelOptions(auth=auth)
            )
            assert good.call_method("a", "echo", b"1").ok()
            bad = Channel()
            assert bad.init(f"127.0.0.1:{s.port}")  # no credentials
            cntl = bad.call_method("a", "echo", b"2")
            assert cntl.failed()
            assert cntl.error_code == ErrorCode.ERPCAUTH
            # the authenticated channel is unaffected
            assert good.call_method("a", "echo", b"3").ok()
        finally:
            s.stop()

    def test_missing_credential_rejected(self):
        s = self._server(SharedSecretAuthenticator("s3cret"))
        try:
            ch = Channel()
            assert ch.init(f"127.0.0.1:{s.port}")  # no auth configured
            cntl = ch.call_method("a", "echo", b"x")
            assert cntl.failed()
            assert cntl.error_code == ErrorCode.ERPCAUTH
        finally:
            s.stop()

    def test_wrong_secret_rejected(self):
        s = self._server(SharedSecretAuthenticator("right"))
        try:
            ch = Channel()
            assert ch.init(
                f"127.0.0.1:{s.port}",
                options=ChannelOptions(auth=SharedSecretAuthenticator("wrong")),
            )
            cntl = ch.call_method("a", "echo", b"x")
            assert cntl.failed()
            assert cntl.error_code == ErrorCode.ERPCAUTH
        finally:
            s.stop()


class TestMemcache:
    @pytest.fixture
    def pair(self):
        server = memcache.MockMemcacheServer()
        assert server.start()
        client = memcache.MemcacheClient(f"127.0.0.1:{server.port}")
        yield server, client
        client.close()
        server.stop()

    def test_store_and_retrieve(self, pair):
        _, c = pair
        assert c.set("k", b"v1", flags=7)
        assert c.get("k") == b"v1"
        assert c.get("missing") is None
        assert not c.add("k", b"v2")  # exists
        assert c.replace("k", b"v2")
        assert c.get("k") == b"v2"
        assert c.delete("k")
        assert not c.delete("k")

    def test_incr_decr(self, pair):
        _, c = pair
        assert c.set("n", b"10")
        assert c.incr("n", 5) == 15
        assert c.decr("n", 3) == 12
        assert c.incr("missing") == "NOT_FOUND"

    def test_get_multi_and_version(self, pair):
        _, c = pair
        c.set("a", b"1")
        c.set("b", b"2")
        assert c.get_multi("a", "b", "zz") == {"a": b"1", "b": b"2"}
        assert "VERSION" in c.version()

    def test_concurrent_clients(self, pair):
        _, c = pair
        errs = []

        def worker(i):
            try:
                for j in range(30):
                    key = f"w{i}"
                    assert c.set(key, b"%d" % j)
                    assert c.get(key) is not None
            except Exception as e:  # pragma: no cover
                errs.append(e)

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs


def make_named_server(name: bytes):
    s = Server()
    s.add_service("svc", {"echo": (lambda c, r, _n=name: _n + b":" + r)})
    assert s.start(0)
    return s


class TestDynamicPartitionChannel:
    def test_mixed_schemes_both_serve(self):
        # scheme /1 (one whole server) and scheme /2 (two half servers)
        servers = [make_named_server(b"s%d" % i) for i in range(3)]
        try:
            url = "list://" + ",".join(
                [
                    f"127.0.0.1:{servers[0].port} 0/1",
                    f"127.0.0.1:{servers[1].port} 0/2",
                    f"127.0.0.1:{servers[2].port} 1/2",
                ]
            )
            dpc = DynamicPartitionChannel()
            assert dpc.init(url)
            got = set()
            for _ in range(40):
                cntl = dpc.call_method("svc", "echo", b"q")
                assert cntl.ok(), cntl.error_text
                got.add(cntl.response_payload)
            # both schemes must have been picked across 40 weighted draws
            assert b"s0:q" in got  # scheme /1
            assert b"s1:qs2:q" in got  # scheme /2 fan-out, merged in order
            dpc.stop()
        finally:
            for s in servers:
                s.stop()

    def test_single_scheme_behaves_like_partition_channel(self):
        servers = [make_named_server(b"p%d" % i) for i in range(2)]
        try:
            url = "list://" + ",".join(
                f"127.0.0.1:{s.port} {i}/2" for i, s in enumerate(servers)
            )
            dpc = DynamicPartitionChannel()
            assert dpc.init(url)
            cntl = dpc.call_method("svc", "echo", b"z")
            assert cntl.ok()
            assert cntl.response_payload == b"p0:zp1:z"
            dpc.stop()
        finally:
            for s in servers:
                s.stop()

    def test_no_tagged_servers_fails(self):
        dpc = DynamicPartitionChannel()
        assert dpc.init("list://127.0.0.1:1 junktag")
        cntl = dpc.call_method("svc", "echo", b"x")
        assert cntl.failed()
        dpc.stop()
