"""Native ObjectPool/FlatMap + fiber mutex/cond tests (reference
test/object_pool_unittest.cpp, flat_map_unittest.cpp,
bthread_mutex/cond/countdown_event unittests)."""

import threading
import time

import pytest

from incubator_brpc_tpu import native
from incubator_brpc_tpu.runtime import (
    CountdownEvent,
    FiberCond,
    FiberMutex,
    contention_profile,
    reset_contention_profile,
)

pytestmark = pytest.mark.skipif(
    not native.NATIVE_AVAILABLE, reason="native runtime unavailable"
)


class TestObjectPool:
    def test_get_return_reuses(self):
        p = native.ObjectPool(item_size=64)
        a = p.get()
        b = p.get()
        assert a and b and a != b
        assert p.live == 2
        p.return_(a)
        assert p.free_count == 1
        c = p.get()  # freelist pop: same address back
        assert c == a
        assert p.live == 2

    def test_many_items_distinct(self):
        p = native.ObjectPool(item_size=16)
        addrs = {p.get() for _ in range(1000)}
        assert len(addrs) == 1000
        assert p.live == 1000


class TestFlatMap:
    def test_insert_get_erase(self):
        m = native.FlatMap()
        m[42] = 4242
        m[0] = 7  # key 0 must work
        assert m[42] == 4242
        assert m[0] == 7
        assert 42 in m and 0 in m and 99 not in m
        assert len(m) == 2
        m[42] = 43
        assert m[42] == 43 and len(m) == 2
        del m[42]
        assert 42 not in m and len(m) == 1
        with pytest.raises(KeyError):
            _ = m[42]
        with pytest.raises(KeyError):
            del m[42]

    def test_growth_and_probe_chains(self):
        m = native.FlatMap(initial_capacity=16)
        n = 10_000
        for i in range(n):
            m[i * 0x9E3779B9] = i
        assert len(m) == n
        assert m.capacity >= n
        for i in range(n):
            assert m[i * 0x9E3779B9] == i

    def test_tombstone_reuse(self):
        m = native.FlatMap(initial_capacity=16)
        for i in range(1, 1000):
            m[i] = i
            del m[i]
        # churn must not blow capacity unboundedly (tombstones are reused
        # on insert and cleared by same-size rehash driven by live count)
        assert len(m) == 0
        assert m.capacity <= 64

    def test_concurrent_mutation_is_safe(self):
        m = native.FlatMap()
        errs = []

        def worker(base):
            try:
                for i in range(2000):
                    k = base + i
                    m[k] = k * 2
                    assert m[k] == k * 2
                    del m[k]
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [
            threading.Thread(target=worker, args=(t * 1_000_000,)) for t in range(6)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        assert len(m) == 0


class TestCaseIgnoredMap:
    """tb_cimap — reference CaseIgnoredFlatMap (HTTP header tables)."""

    def test_case_insensitive_lookup_preserves_spelling(self):
        from incubator_brpc_tpu.native import CaseIgnoredMap

        m = CaseIgnoredMap()
        m["Content-Type"] = "text/plain"
        assert m["content-type"] == "text/plain"
        assert m["CONTENT-TYPE"] == "text/plain"
        assert "cOnTeNt-TyPe" in m
        assert m.keys() == ["Content-Type"]  # original spelling kept
        m["CONTENT-type"] = "application/json"  # replace via other casing
        assert len(m) == 1
        assert m["content-type"] == "application/json"

    def test_erase_and_missing(self):
        from incubator_brpc_tpu.native import CaseIgnoredMap

        m = CaseIgnoredMap()
        m["X-A"] = "1"
        m["X-B"] = ""
        assert m.get("x-b") == ""  # empty values round-trip
        del m["x-a"]
        assert m.get("X-A") is None
        with pytest.raises(KeyError):
            del m["x-a"]
        assert len(m) == 1

    def test_growth_and_tombstones(self):
        from incubator_brpc_tpu.native import CaseIgnoredMap

        m = CaseIgnoredMap(initial_capacity=4)
        for i in range(200):
            m[f"Header-{i}"] = str(i)
        for i in range(0, 200, 2):
            del m[f"header-{i}"]
        for i in range(200):
            want = None if i % 2 == 0 else str(i)
            assert m.get(f"HEADER-{i}") == want
        assert len(m) == 100


class TestMRUCache:
    """tb_mru — reference MRUCache (capacity-bounded, LRU eviction)."""

    def test_eviction_order(self):
        from incubator_brpc_tpu.native import MRUCache

        c = MRUCache(3)
        for k in (1, 2, 3):
            c.put(k, k * 10)
        assert c.get(1) == 10  # freshen 1
        c.put(4, 40)  # evicts 2 (least recently used)
        assert 2 not in c
        assert c.get(1) == 10 and c.get(3) == 30 and c.get(4) == 40
        assert len(c) == 3

    def test_put_replaces_and_freshens(self):
        from incubator_brpc_tpu.native import MRUCache

        c = MRUCache(2)
        assert c.put(7, 1) is False
        assert c.put(7, 2) is True  # replace
        c.put(8, 3)
        c.put(7, 4)  # freshen 7
        c.put(9, 5)  # evicts 8
        assert 8 not in c and c.get(7) == 4 and c.get(9) == 5


class TestWriteBacklogContinuation:
    def test_multi_mb_backlog_drains_past_the_iovec_ceiling(self):
        # VERDICT r3 weak #6: 256 iovecs x 8KB blocks = 2MB per writev;
        # the continuation loop must push a much larger backlog of SMALL
        # blocks through one call boundary per kernel-buffer fill
        import socket as pysock
        import threading

        from incubator_brpc_tpu.iobuf import IOBuf

        a, b = pysock.socketpair()
        a.setblocking(False)
        buf = IOBuf()
        chunk = bytes(range(256)) * 16  # 4KB pieces -> many blocks
        total = 8 << 20  # 8 MB across ~2000 refs
        for _ in range(total // len(chunk)):
            buf.append(chunk)
        got = bytearray()
        done = threading.Event()

        def reader():
            while len(got) < total:
                data = b.recv(1 << 20)
                if not data:
                    break
                got.extend(data)
            done.set()

        t = threading.Thread(target=reader)
        t.start()
        calls = 0
        import time as _t

        deadline = _t.monotonic() + 30
        while len(buf) and _t.monotonic() < deadline:
            rc = buf.cut_into_fd(a.fileno(), max_bytes=total)
            calls += 1
            if rc <= 0:
                _t.sleep(0.005)  # EAGAIN: kernel buffer full, reader drains
        assert len(buf) == 0
        assert done.wait(10)
        a.close(), b.close()
        t.join(5)
        assert bytes(got) == chunk * (total // len(chunk))
        # one call per kernel-buffer fill, NOT one per 2MB iovec window
        assert calls < total // (2 << 20) * 100  # sanity ceiling


class TestFiberMutex:
    def test_mutual_exclusion(self):
        m = FiberMutex()
        counter = [0]

        def worker():
            for _ in range(500):
                with m:
                    v = counter[0]
                    counter[0] = v + 1

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert counter[0] == 4000

    def test_try_acquire_and_timeout(self):
        m = FiberMutex()
        assert m.try_acquire()
        assert not m.try_acquire()
        t0 = time.monotonic()
        assert not m.acquire(timeout=0.1)
        assert 0.05 < time.monotonic() - t0 < 2.0
        m.release()
        assert m.acquire(timeout=0.1)
        m.release()

    def test_contention_is_profiled(self):
        reset_contention_profile()
        m = FiberMutex()

        def holder():
            with m:
                time.sleep(0.05)

        t = threading.Thread(target=holder)
        t.start()
        time.sleep(0.01)
        with m:  # contended: must be recorded
            pass
        t.join()
        rows = contention_profile()
        assert rows, "contended acquire not sampled"
        total_wait = sum(us for _, _, us in rows)
        assert total_wait > 10_000  # waited tens of ms


class TestFiberCond:
    def test_notify_one_wakes_waiter(self):
        m = FiberMutex()
        cond = FiberCond()
        ready = []

        def waiter():
            with m:
                while not ready:
                    cond.wait(m)
                ready.append("seen")

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.05)
        with m:
            ready.append(True)
        cond.notify_one()
        t.join(timeout=5)
        assert not t.is_alive()
        assert "seen" in ready

    def test_wait_timeout(self):
        m = FiberMutex()
        cond = FiberCond()
        with m:
            assert cond.wait(m, timeout=0.1) is False
        assert not m.locked


class TestCountdownEvent:
    def test_signals_release_waiters(self):
        ev = CountdownEvent(3)
        done = threading.Event()

        def waiter():
            ev.wait()
            done.set()

        t = threading.Thread(target=waiter)
        t.start()
        ev.signal()
        ev.signal()
        assert not done.wait(timeout=0.1)
        ev.signal()
        assert done.wait(timeout=5)
        t.join()

    def test_wait_timeout(self):
        ev = CountdownEvent(1)
        assert ev.wait(timeout=0.05) is False
        ev.signal()
        assert ev.wait(timeout=1)


class TestContainersLoadBearing:
    """The native containers back live framework paths (round-2 verdict:
    'integration is what makes a component count')."""

    def test_socket_registry_runs_on_native_respool(self):
        from incubator_brpc_tpu.native import NATIVE_AVAILABLE
        from incubator_brpc_tpu.transport.sock import _registry

        if not NATIVE_AVAILABLE:
            pytest.skip("native runtime unavailable")
        assert _registry._pool is not None  # tb_respool, not a Python slab
        before = _registry.live_count()

        from incubator_brpc_tpu.rpc import Channel, Server

        srv = Server()
        srv.add_service("t", {"echo": lambda cntl, req: req})
        assert srv.start(0)
        try:
            ch = Channel()
            assert ch.init(f"127.0.0.1:{srv.port}")
            assert ch.call_method("t", "echo", b"x").ok()
            assert _registry.live_count() > before  # live sockets slabbed
            sock = ch._socket_map.get_or_create(ch._single_server)
            sid = sock.id
            from incubator_brpc_tpu.transport.sock import address_socket

            assert address_socket(sid) is sock
            sock.recycle()
            assert address_socket(sid) is None  # stale version: ABA-safe
        finally:
            srv.stop()
            srv.join(timeout=5)

    def test_server_method_map_runs_on_native_flatmap(self):
        from incubator_brpc_tpu.native import NATIVE_AVAILABLE
        from incubator_brpc_tpu.rpc import Server

        if not NATIVE_AVAILABLE:
            pytest.skip("native runtime unavailable")
        srv = Server()
        srv.add_service("svc", {"a": lambda c, r: r, "b": lambda c, r: r})
        assert srv._methods._fm is not None
        assert len(srv._methods._fm) == 2  # the tb_flatmap holds the rows
        assert srv._methods.get("svc.a") is not None
        assert srv._methods.get("svc.a").full_name == "svc.a"
        assert srv._methods.get("svc.nope") is None
        assert "svc.b" in srv._methods

    def test_iobuf_handles_ride_the_object_pool(self):
        # IOBuf handles are pooled (placement-new over tb_objpool slots):
        # a create/destroy churn must recycle slots, not grow live count
        import ctypes

        from incubator_brpc_tpu.iobuf import IOBuf
        from incubator_brpc_tpu.native import LIB, NATIVE_AVAILABLE

        if not NATIVE_AVAILABLE:
            pytest.skip("native runtime unavailable")

        def stats():
            live = ctypes.c_size_t()
            free = ctypes.c_size_t()
            LIB.tb_iobuf_handle_pool_stats(ctypes.byref(live), ctypes.byref(free))
            return live.value, free.value

        bufs = [IOBuf() for _ in range(32)]
        live1, _ = stats()
        del bufs
        live2, free2 = stats()
        assert live2 <= live1 - 32  # all 32 handles returned to the pool
        assert free2 >= 32  # ...and parked for reuse, never freed
        again = [IOBuf() for _ in range(16)]
        live3, free3 = stats()
        assert live3 == live2 + 16
        assert free3 <= free2 - 16 + 1  # slots came from the free list
        del again
