"""HTTP protocol + builtin portal tests (reference
test/brpc_http_rpc_protocol_unittest.cpp for parse conformance,
brpc_builtin_service_unittest.cpp for page coverage: a real server is
started and each endpoint is fetched over a real TCP connection)."""

import time

import pytest

from incubator_brpc_tpu.protocol import http as http_mod
from incubator_brpc_tpu.protocol.tbus_std import ParseError
from incubator_brpc_tpu.rpc import Channel, Server
from incubator_brpc_tpu.utils.flags import define_flag, flag_registry, set_flag


class TestParse:
    def test_simple_get(self):
        frame, consumed = http_mod.parse(b"GET /vars?prefix=socket HTTP/1.1\r\nHost: x\r\n\r\n")
        assert consumed > 0
        assert frame.method == "GET"
        assert frame.path == "/vars"
        assert frame.query == {"prefix": "socket"}
        assert frame.headers["host"] == "x"
        assert frame.body == b""

    def test_post_with_body(self):
        raw = b"POST /x HTTP/1.1\r\nContent-Length: 5\r\n\r\nhello"
        frame, consumed = http_mod.parse(raw)
        assert consumed == len(raw)
        assert frame.body == b"hello"

    def test_incomplete_returns_none(self):
        assert http_mod.parse(b"GET /x HTTP/1.1\r\nHost") == (None, 0)
        raw = b"POST /x HTTP/1.1\r\nContent-Length: 50\r\n\r\nshort"
        assert http_mod.parse(raw) == (None, 0)

    def test_not_http_raises(self):
        with pytest.raises(ParseError):
            http_mod.parse(b"TPRC\x00\x00\x00\x00garbage")

    def test_bad_content_length_is_parse_error(self):
        with pytest.raises(ParseError):
            http_mod.parse(b"POST /x HTTP/1.1\r\nContent-Length: abc\r\n\r\n")
        with pytest.raises(ParseError):
            http_mod.parse(b"POST /x HTTP/1.1\r\nContent-Length: -5\r\n\r\n")
        with pytest.raises(ParseError):
            http_mod.parse_header(b"POST /x HTTP/1.1\r\nContent-Length: abc\r\n\r\n")

    def test_parse_header_sizes_the_frame(self):
        raw = b"POST /x HTTP/1.1\r\nContent-Length: 5\r\n\r\nhello"
        assert http_mod.parse_header(raw) == len(raw)
        assert http_mod.parse_header(b"GET /x HTTP/1.1\r\nHost") is None
        with pytest.raises(ParseError):
            http_mod.parse_header(b"TPRC\x00\x00\x00\x00")

    def test_two_pipelined_requests_cut_one_at_a_time(self):
        raw = b"GET /a HTTP/1.1\r\n\r\nGET /b HTTP/1.1\r\n\r\n"
        frame, consumed = http_mod.parse(raw)
        assert frame.path == "/a"
        frame2, consumed2 = http_mod.parse(raw[consumed:])
        assert frame2.path == "/b"
        assert consumed + consumed2 == len(raw)


def _len_handler(frame):
    import hashlib

    body = frame.body
    return (
        200,
        "text/plain",
        f"{len(body)}:{hashlib.sha1(body).hexdigest()}".encode(),
    )


@pytest.fixture
def portal_server():
    server = Server()
    server.add_service("demo", {"echo": lambda cntl, req: req})
    server.add_http_handler(
        "/custom", lambda frame: (200, "text/plain", b"custom-page")
    )
    server.add_http_handler("/demo/len", _len_handler)
    assert server.start(0)
    yield server
    server.stop()
    server.join(timeout=5)


def fetch(server, path, method="GET", body=b""):
    return http_mod.http_call("127.0.0.1", server.port, path, method=method, body=body)


class TestPortal:
    def test_health(self, portal_server):
        status, _, body = fetch(portal_server, "/health")
        assert status == 200 and body == b"OK"

    def test_index_links(self, portal_server):
        status, headers, body = fetch(portal_server, "/")
        assert status == 200
        assert b"/vars" in body and b"/status" in body and b"/flags" in body

    def test_vars_shows_live_counters(self, portal_server):
        # drive real RPC traffic first so bvars move
        ch = Channel()
        assert ch.init(f"127.0.0.1:{portal_server.port}")
        for _ in range(3):
            assert ch.call_method("demo", "echo", b"x").ok()
        status, _, body = fetch(portal_server, "/vars")
        assert status == 200
        assert b"socket_in_bytes : " in body
        status, _, body = fetch(portal_server, "/vars?prefix=socket")
        assert status == 200
        assert b"socket_in_bytes" in body and b"method_" not in body

    def test_status_shows_method_rows(self, portal_server):
        ch = Channel()
        assert ch.init(f"127.0.0.1:{portal_server.port}")
        for _ in range(5):
            assert ch.call_method("demo", "echo", b"y").ok()
        # the server's on_responded accounting runs after the response
        # write, so the client can observe its 5th reply a beat before the
        # count does: poll briefly instead of racing it
        import time as _time

        text = ""
        for _ in range(50):
            status, _, body = fetch(portal_server, "/status")
            assert status == 200
            text = body.decode()
            if "count=5" in text:
                break
            _time.sleep(0.02)
        assert "demo.echo" in text
        assert "count=5" in text

    def test_flags_list_and_reloadable_set(self, portal_server):
        define_flag(
            "test_http_reloadable", 7, "test flag", lambda v: v > 0
        )
        status, _, body = fetch(portal_server, "/flags")
        assert status == 200
        assert b"test_http_reloadable" in body
        # set a reloadable flag through the portal
        status, _, body = fetch(
            portal_server, "/flags/test_http_reloadable?setvalue=9"
        )
        assert status == 200
        assert flag_registry.get("test_http_reloadable") == 9
        # validator rejects
        status, _, _ = fetch(
            portal_server, "/flags/test_http_reloadable?setvalue=-1"
        )
        assert status == 400
        # non-reloadable flags are refused (reloadable_flags.h gate)
        status, _, _ = fetch(portal_server, "/flags/event_dispatcher_num?setvalue=2")
        assert status == 403
        assert flag_registry.get("event_dispatcher_num") == 4  # default kept

    def test_rpcz_records_real_calls(self, portal_server):
        assert set_flag("enable_rpcz", True)
        try:
            ch = Channel()
            assert ch.init(f"127.0.0.1:{portal_server.port}")
            assert ch.call_method("demo", "echo", b"traced").ok()
            status, _, body = fetch(portal_server, "/rpcz")
            assert status == 200
            assert b"demo.echo" in body
        finally:
            set_flag("enable_rpcz", False)

    def test_custom_handler_and_404(self, portal_server):
        status, _, body = fetch(portal_server, "/custom")
        assert status == 200 and body == b"custom-page"
        status, _, _ = fetch(portal_server, "/definitely-missing")
        assert status == 404

    def test_connections_page(self, portal_server):
        status, _, body = fetch(portal_server, "/connections")
        assert status == 200
        assert str(portal_server.port).encode() in body

    def test_progressive_chunked_response(self, portal_server):
        """ProgressiveAttachment analog: a handler returning an iterator
        streams chunks; the client sees data before the producer finishes
        (progressive_attachment.{h,cpp})."""
        import socket as pysocket
        import threading
        import time

        gate = threading.Event()

        def body():
            yield b"first-chunk"
            gate.wait(timeout=5)  # hold the stream open until released
            yield b"second-chunk"

        srv = Server()
        srv.add_http_handler(
            "/streamed", lambda frame: (200, "text/plain", body())
        )
        assert srv.start(0)
        try:
            with pysocket.create_connection(("127.0.0.1", srv.port)) as conn:
                conn.sendall(b"GET /streamed HTTP/1.1\r\n\r\n")
                conn.settimeout(5)
                got = b""
                while b"first-chunk" not in got:
                    got += conn.recv(65536)
                # first chunk arrived while the producer is still blocked
                assert b"second-chunk" not in got
                assert b"Transfer-Encoding: chunked" in got
                gate.set()
                while b"0\r\n\r\n" not in got:
                    got += conn.recv(65536)
                assert b"second-chunk" in got
        finally:
            srv.stop()

    def test_pipelined_request_waits_for_stream(self, portal_server):
        """A pipelined request behind a progressive response must not have
        its response interleave with the chunks (in-order contract)."""
        import socket as pysocket
        import threading

        gate = threading.Event()

        def body():
            yield b"AAA"
            gate.wait(timeout=5)
            yield b"BBB"

        srv = Server()
        srv.add_http_handler("/s", lambda frame: (200, "text/plain", body()))
        assert srv.start(0)
        try:
            with pysocket.create_connection(("127.0.0.1", srv.port)) as conn:
                conn.sendall(
                    b"GET /s HTTP/1.1\r\n\r\nGET /health HTTP/1.1\r\n"
                    b"Connection: close\r\n\r\n"
                )
                conn.settimeout(5)
                got = b""
                while b"AAA" not in got:
                    got += conn.recv(65536)
                # second response must NOT have arrived mid-stream
                assert b"OK" not in got.split(b"AAA")[-1]
                gate.set()
                while b'HTTP/1.1 200 OK\r\nContent-Length: 2' not in got:
                    data = conn.recv(65536)
                    if not data:
                        break
                    got += data
                # stream terminator precedes the second response
                term = got.find(b"0\r\n\r\n")
                second = got.find(b"Content-Length: 2")
                assert 0 < term < second
        finally:
            srv.stop()

    def test_str_body_is_coerced(self, portal_server):
        srv = Server()
        srv.add_http_handler("/str", lambda frame: (200, "text/plain", "plain-str"))
        assert srv.start(0)
        try:
            status, _, body = http_mod.http_call("127.0.0.1", srv.port, "/str")
            assert (status, body) == (200, b"plain-str")
        finally:
            srv.stop()

    def test_http_call_decodes_chunked(self, portal_server):
        srv = Server()
        srv.add_http_handler(
            "/gen",
            lambda frame: (200, "text/plain", (b"x%d|" % i for i in range(5))),
        )
        assert srv.start(0)
        try:
            status, headers, body = http_mod.http_call("127.0.0.1", srv.port, "/gen")
            assert status == 200
            assert body == b"x0|x1|x2|x3|x4|"
        finally:
            srv.stop()

    def test_head_has_no_body(self, portal_server):
        import socket as pysocket

        with pysocket.create_connection(("127.0.0.1", portal_server.port)) as conn:
            conn.sendall(
                b"HEAD /health HTTP/1.1\r\n\r\nGET /version HTTP/1.1\r\n"
                b"Connection: close\r\n\r\n"
            )
            conn.settimeout(5)
            raw = b""
            while True:
                try:
                    data = conn.recv(65536)
                except OSError:
                    break
                if not data:
                    break
                raw += data
        # first response: headers only (Content-Length present, no body);
        # second response parses cleanly right after it
        first_end = raw.find(b"\r\n\r\n") + 4
        first = raw[:first_end]
        assert b"Content-Length: 2" in first  # what GET would return
        second = raw[first_end:]
        assert second.startswith(b"HTTP/1.1 200")

    def test_pipelined_responses_in_request_order(self, portal_server):
        import socket as pysocket

        with pysocket.create_connection(("127.0.0.1", portal_server.port)) as conn:
            # /status is slower than /health; order must still hold
            conn.sendall(
                b"GET /status HTTP/1.1\r\n\r\nGET /health HTTP/1.1\r\n"
                b"Connection: close\r\n\r\n"
            )
            conn.settimeout(5)
            raw = b""
            while True:
                try:
                    data = conn.recv(65536)
                except OSError:
                    break
                if not data:
                    break
                raw += data
        assert raw.count(b"HTTP/1.1 200") == 2
        first_body_at = raw.find(b"\r\n\r\n") + 4
        assert b"server " in raw[first_body_at : first_body_at + 40]  # /status first

    def test_binary_and_http_share_the_port(self, portal_server):
        """Protocol sniffing: the same listening port serves tbus_std RPCs
        and HTTP pages (InputMessenger tries protocols in order)."""
        ch = Channel()
        assert ch.init(f"127.0.0.1:{portal_server.port}")
        assert ch.call_method("demo", "echo", b"bin").response_payload == b"bin"
        status, _, body = fetch(portal_server, "/health")
        assert status == 200 and body == b"OK"


class TestChunkedResponsesOnChannels:
    def test_channel_receives_progressive_response(self):
        """A Channel(protocol='http') consuming a handler that streams its
        body chunked — the stateful response decode (the reference's full
        http client reads chunked responses the same way)."""
        from incubator_brpc_tpu.rpc import ChannelOptions, Controller

        def streamy(cntl, req):
            def gen():
                for i in range(64):
                    yield b"chunk-%03d|" % i

            return gen()

        srv = Server()
        srv.add_service("s", {"stream": streamy, "plain": lambda c, r: r})
        assert srv.start(0)
        try:
            ch = Channel()
            assert ch.init(
                f"127.0.0.1:{srv.port}", options=ChannelOptions(protocol="http")
            )
            cntl = ch.call_method(
                "s", "stream", b"", cntl=Controller(timeout_ms=30000)
            )
            assert cntl.ok(), cntl.error_text
            want = b"".join(b"chunk-%03d|" % i for i in range(64))
            assert cntl.response_payload == want
            # the connection stays usable for an ordinary response after
            cntl2 = ch.call_method(
                "s", "plain", b"pp", cntl=Controller(timeout_ms=30000)
            )
            assert cntl2.ok(), cntl2.error_text
            assert cntl2.response_payload == b"pp"
        finally:
            srv.stop()


class TestPortalCompleteness:
    """Round-4 pages: /protobufs /dir /threads /vlog (reference
    builtin/list_service, dir_service, threads_service, vlog_service)."""

    def test_protobufs_lists_method_schemas(self, portal_server):
        status, _, body = fetch(portal_server, "/protobufs")
        assert status == 200
        assert b"demo.echo" in body and b"handler=" in body
        # filtered view
        status, _, body = fetch(portal_server, "/protobufs/demo")
        assert status == 200 and b"demo.echo" in body

    def test_protobufs_shows_device_kernel_contract(self):
        from incubator_brpc_tpu.rpc import Server, device_method

        srv = Server()
        srv.add_service(
            "dsvc", {"k": device_method(lambda d, n: (d, n), width=128)}
        )
        assert srv.start(0)
        try:
            status, _, body = fetch(srv, "/protobufs")
            assert status == 200
            assert b"device_kernel=fp:" in body and b"width=128" in body
        finally:
            srv.stop()

    def test_dir_lists_and_serves_files(self, portal_server, tmp_path):
        from incubator_brpc_tpu.utils.flags import get_flag, set_flag

        # OFF by default: an unauthenticated file read must be opt-in
        status, _, _ = fetch(portal_server, "/dir")
        assert status == 403
        assert set_flag("enable_dir_service", True)
        try:
            f = tmp_path / "hello.txt"
            f.write_text("dir-page-payload")
            status, ctype, body = fetch(portal_server, f"/dir/{tmp_path}")
            assert status == 200 and b"hello.txt" in body
            status, _, body = fetch(portal_server, f"/dir/{f}")
            assert status == 200 and body == b"dir-page-payload"
            status, _, _ = fetch(portal_server, "/dir/no/such/path")
            assert status == 404
        finally:
            set_flag("enable_dir_service", False)

    def test_threads_dumps_live_stacks(self, portal_server):
        status, _, body = fetch(portal_server, "/threads")
        assert status == 200
        assert b"-- thread " in body
        # the reactor and worker threads appear with real frames
        assert b"File \"" in body

    def test_vlog_lists_and_sets_levels(self, portal_server):
        status, _, body = fetch(portal_server, "/vlog")
        assert status == 200
        assert b"incubator_brpc_tpu" in body
        status, _, body = fetch(
            portal_server, "/vlog?set=incubator_brpc_tpu.test_vlog:DEBUG"
        )
        assert status == 200 and b"DEBUG" in body
        import logging as _logging

        assert (
            _logging.getLogger("incubator_brpc_tpu.test_vlog").level
            == _logging.DEBUG
        )
        status, _, _ = fetch(portal_server, "/vlog?set=bad-spec")
        assert status == 400


class TestPortalDepth:
    """Round-3 portal pages: /sockets /fibers /ids + pprof folded output
    (reference builtin/sockets_service, /bthreads, /ids, pprof_service)."""

    def test_sockets_lists_live_connections(self, portal_server):
        ch = Channel()
        assert ch.init(f"127.0.0.1:{portal_server.port}")
        assert ch.call_method("demo", "echo", b"x").ok()
        status, _, body = fetch(portal_server, "/sockets")
        assert status == 200
        assert b"live sockets:" in body
        assert b"state=up" in body
        assert b"Socket" in body

    def test_fibers_shows_scheduler_stats(self, portal_server):
        status, _, body = fetch(portal_server, "/fibers")
        assert status == 200
        for key in (b"workers:", b"idle:", b"queued_remote:", b"fibers_run:"):
            assert key in body

    def test_ids_shows_slab_occupancy(self, portal_server):
        ch = Channel()
        assert ch.init(f"127.0.0.1:{portal_server.port}")
        assert ch.call_method("demo", "echo", b"x").ok()
        status, _, body = fetch(portal_server, "/ids")
        assert status == 200
        assert b"call_ids: slots=" in body
        assert b"sockets: live=" in body

    def test_pprof_folded_profile(self, portal_server):
        # background load so the sampler sees stacks
        import threading as _t

        stop = _t.Event()

        def burn():
            ch = Channel()
            assert ch.init(f"127.0.0.1:{portal_server.port}")
            while not stop.is_set():
                ch.call_method("demo", "echo", b"load")

        th = _t.Thread(target=burn)
        th.start()
        try:
            status, _, body = fetch(
                portal_server, "/pprof/profile?seconds=0.3"
            )
        finally:
            stop.set()
            th.join()
        assert status == 200
        lines = [l for l in body.decode().splitlines() if l.strip()]
        assert lines, "no folded samples"
        # folded format: 'frame;frame;... count'
        for line in lines[:5]:
            stack, _, count = line.rpartition(" ")
            assert stack and count.isdigit(), line

    def test_pprof_contention_folded(self, portal_server):
        status, _, body = fetch(portal_server, "/pprof/contention")
        assert status == 200  # may be empty without contention; format only


class TestHeapProfile:
    def test_heap_page_start_snapshot_stop(self, portal_server):
        from incubator_brpc_tpu.builtin import hotspots

        status, _, body = fetch(portal_server, "/hotspots/heap")
        assert status == 200 and b"off" in body
        try:
            status, _, body = fetch(portal_server, "/hotspots/heap?start=1")
            assert status == 200
            # allocate something attributable, then snapshot
            ch = Channel()
            assert ch.init(f"127.0.0.1:{portal_server.port}")
            for _ in range(5):
                assert ch.call_method("demo", "echo", b"h" * 2048).ok()
            status, _, body = fetch(portal_server, "/hotspots/heap")
            assert status == 200
            assert b"tracked live bytes:" in body
            assert b"by allocation site" in body
            status, _, body = fetch(
                portal_server, "/pprof/heap"
            )
            assert status == 200  # folded: 'file:line;... bytes' lines
            for line in body.decode().splitlines()[:3]:
                stack, _, weight = line.rpartition(" ")
                assert weight.isdigit()
        finally:
            fetch(portal_server, "/hotspots/heap?stop=1")
            assert not hotspots.heap_profiling_active()


class TestHttpChannelClient:
    """HTTP as a first-class Channel protocol (reference
    http_rpc_protocol.cpp client path): same Socket stack, FIFO response
    correlation, pipelining on one keep-alive connection."""

    def test_echo_over_http_channel(self, portal_server):
        from incubator_brpc_tpu.rpc import ChannelOptions

        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{portal_server.port}",
            options=ChannelOptions(protocol="http"),
        )
        cntl = ch.call_method("demo", "echo", b"over http")
        assert cntl.ok(), cntl.error_text
        assert cntl.response_payload == b"over http"
        assert cntl.http_status == 200

    def test_http_error_status_maps_to_ehttp(self, portal_server):
        from incubator_brpc_tpu.rpc import ChannelOptions
        from incubator_brpc_tpu.utils.status import ErrorCode

        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{portal_server.port}",
            options=ChannelOptions(protocol="http"),
        )
        cntl = ch.call_method("demo", "missing_method", b"")
        assert cntl.failed()
        assert cntl.error_code == ErrorCode.EHTTP
        assert "404" in cntl.error_text

    def test_concurrent_pipelined_http_calls(self, portal_server):
        import threading

        from incubator_brpc_tpu.rpc import ChannelOptions

        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{portal_server.port}",
            options=ChannelOptions(protocol="http", timeout_ms=10000),
        )
        errs = []

        def worker(i):
            for j in range(15):
                body = f"{i}:{j}".encode()
                c = ch.call_method("demo", "echo", body)
                if c.failed() or c.response_payload != body:
                    errs.append((i, j, c.error_code, c.error_text))

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs, errs[:3]

    def test_http_channel_and_binary_share_the_server(self, portal_server):
        from incubator_brpc_tpu.rpc import ChannelOptions

        hch = Channel()
        assert hch.init(
            f"127.0.0.1:{portal_server.port}",
            options=ChannelOptions(protocol="http"),
        )
        bch = Channel()
        assert bch.init(f"127.0.0.1:{portal_server.port}")
        for i in range(5):
            hc = hch.call_method("demo", "echo", f"h{i}".encode())
            bc = bch.call_method("demo", "echo", f"b{i}".encode())
            assert hc.ok() and hc.response_payload == f"h{i}".encode()
            assert bc.ok() and bc.response_payload == f"b{i}".encode()


class TestVarsSeries:
    def test_series_json_has_sampled_points(self, portal_server):
        import json as _json
        import time as _time

        ch = Channel()
        assert ch.init(f"127.0.0.1:{portal_server.port}")
        # traffic + wait for 2+ sampler ticks (1 Hz)
        deadline = _time.monotonic() + 6
        obj = {}
        while _time.monotonic() < deadline:
            assert ch.call_method("demo", "echo", b"tick").ok()
            status, _, body = fetch(portal_server, "/vars/series.json")
            assert status == 200
            obj = _json.loads(body)
            s = obj.get("socket_in_bytes_per_second")
            if s and len(s["values"]) >= 2:
                break
            _time.sleep(0.5)
        s = obj.get("socket_in_bytes_per_second")
        assert s and len(s["values"]) >= 2, obj.keys()
        assert len(s["ages_s"]) == len(s["values"])
        # newest point is recent, ages ascend toward the past
        assert s["ages_s"][-1] <= s["ages_s"][0] + 1e-6 or len(s["ages_s"]) == 1


class TestChunkedRequests:
    """Chunked request bodies (RFC 9112 §7.1) dechunked up to the cut
    window — the reference accepts them via http_parser; ours bounds them."""

    def _post_chunked(self, port, path, chunks, trailers=b""):
        import socket as pysock

        body = b"".join(
            b"%x\r\n%s\r\n" % (len(c), c) for c in chunks
        ) + b"0\r\n" + trailers + b"\r\n"
        req = (
            f"POST {path} HTTP/1.1\r\n"
            "Host: t\r\n"
            "Transfer-Encoding: chunked\r\n"
            "Connection: close\r\n\r\n"
        ).encode() + body
        conn = pysock.create_connection(("127.0.0.1", port), timeout=10)
        conn.sendall(req)
        resp = b""
        while True:
            data = conn.recv(65536)
            if not data:
                break
            resp += data
        conn.close()
        return resp

    def test_chunked_post_reassembles(self, portal_server):
        resp = self._post_chunked(
            portal_server.port, "/demo/echo", [b"hello ", b"chunked ", b"world"]
        )
        assert resp.startswith(b"HTTP/1.1 200")
        assert b"hello chunked world" in resp

    def test_chunked_with_trailers(self, portal_server):
        resp = self._post_chunked(
            portal_server.port, "/demo/echo", [b"tail"],
            trailers=b"X-Checksum: abc\r\n",
        )
        assert resp.startswith(b"HTTP/1.1 200")
        assert b"tail" in resp

    def test_malformed_chunk_size_kills_connection(self, portal_server):
        import socket as pysock

        req = (
            b"POST /demo/echo HTTP/1.1\r\nHost: t\r\n"
            b"Transfer-Encoding: chunked\r\n\r\n"
            b"ZZZ\r\nnope\r\n0\r\n\r\n"
        )
        conn = pysock.create_connection(
            ("127.0.0.1", portal_server.port), timeout=10
        )
        conn.sendall(req)
        resp = b""
        while True:
            data = conn.recv(65536)
            if not data:
                break
            resp += data
        conn.close()
        assert resp == b""  # connection failed, no response

    def test_chunked_header_signals_stateful_takeover(self):
        from incubator_brpc_tpu.protocol import http as http_mod

        # parse_header returns None for chunked requests — the messenger
        # pins the protocol and parse_conn resumes dechunking statefully
        # (bounded by max_body_size, NOT the peek window)
        wire = (
            b"POST /a/b HTTP/1.1\r\nHost: t\r\n"
            b"Transfer-Encoding: Chunked\r\n\r\n"
            b"3\r\nabc\r\n0\r\n\r\n"
        )
        assert http_mod.parse_header(wire) is None
        frame, consumed = http_mod.parse(wire)  # inline path still cuts
        assert consumed == len(wire)
        assert frame.body == b"abc"

    def test_mixed_case_and_multi_codings(self):
        from incubator_brpc_tpu.protocol import http as http_mod
        from incubator_brpc_tpu.protocol.tbus_std import FatalParseError

        # 'gzip, chunked' would hand handlers still-encoded bytes: refuse
        bad = (
            b"POST /a/b HTTP/1.1\r\nHost: t\r\n"
            b"Transfer-Encoding: gzip, chunked\r\n\r\n"
            b"3\r\nabc\r\n0\r\n\r\n"
        )
        with pytest.raises(FatalParseError):
            http_mod.parse_header(bad)
        with pytest.raises(FatalParseError):
            http_mod.parse(bad)

    def test_10mb_chunked_upload(self, portal_server):
        # far beyond the 64 KiB peek window: the stateful parse_conn decode
        # must reassemble it (VERDICT r3 item 7's acceptance test)
        blob = bytes(range(256)) * 4096 * 10  # 10 MiB
        chunks = [blob[i : i + 57_000] for i in range(0, len(blob), 57_000)]
        resp = self._post_chunked(portal_server.port, "/demo/len", chunks)
        assert resp.startswith(b"HTTP/1.1 200")
        import hashlib

        expect = f"{len(blob)}:{hashlib.sha1(blob).hexdigest()}".encode()
        assert expect in resp

    def test_chunked_body_over_max_body_size_kills_conn(self, portal_server):
        import socket as pysock

        from incubator_brpc_tpu.utils.flags import get_flag, set_flag_unchecked

        old = get_flag("max_body_size")
        set_flag_unchecked("max_body_size", 100_000)
        try:
            conn = pysock.create_connection(
                ("127.0.0.1", portal_server.port), timeout=10
            )
            head = (
                b"POST /demo/len HTTP/1.1\r\nHost: t\r\n"
                b"Transfer-Encoding: chunked\r\n\r\n"
            )
            chunk = b"x" * 60_000
            conn.sendall(head + b"%x\r\n%s\r\n" % (len(chunk), chunk))
            conn.sendall(b"%x\r\n%s\r\n" % (len(chunk), chunk))  # > 100 KB
            resp = b""
            while True:
                try:
                    data = conn.recv(65536)
                except OSError:
                    break
                if not data:
                    break
                resp += data
            conn.close()
            assert resp == b""  # the connection was failed, not wedged
        finally:
            set_flag_unchecked("max_body_size", old)


class TestProgressiveReader:
    """add_http_handler(progressive=True): the handler consumes the body
    WHILE chunks arrive (reference progressive_reader.h +
    input_messenger.cpp:343-351)."""

    def test_handler_streams_while_uploading(self):
        import hashlib
        import socket as pysock
        import threading as _threading

        seen_progressive = []

        def upload(frame):
            from incubator_brpc_tpu.protocol.http import ProgressiveReader

            body = frame.body
            if isinstance(body, ProgressiveReader):
                seen_progressive.append(True)
                h = hashlib.sha1()
                n = 0
                while True:
                    piece = body.read(timeout=20)
                    if not piece:
                        break
                    h.update(piece)
                    n += len(piece)
                return 200, "text/plain", f"{n}:{h.hexdigest()}".encode()
            return 200, "text/plain", b"buffered"

        srv = Server()
        srv.add_http_handler("/up", upload, progressive=True)
        assert srv.start(0)
        try:
            blob = b"progressive!" * 100_000  # 1.2 MB
            conn = pysock.create_connection(("127.0.0.1", srv.port), timeout=10)
            conn.sendall(
                b"POST /up HTTP/1.1\r\nHost: t\r\n"
                b"Transfer-Encoding: chunked\r\nConnection: close\r\n\r\n"
            )
            # dribble the chunks so the handler demonstrably runs mid-upload
            for i in range(0, len(blob), 200_000):
                c = blob[i : i + 200_000]
                conn.sendall(b"%x\r\n%s\r\n" % (len(c), c))
            conn.sendall(b"0\r\n\r\n")
            resp = b""
            while True:
                data = conn.recv(65536)
                if not data:
                    break
                resp += data
            conn.close()
            assert seen_progressive, "handler did not get a ProgressiveReader"
            import hashlib as _h

            expect = f"{len(blob)}:{_h.sha1(blob).hexdigest()}".encode()
            assert resp.startswith(b"HTTP/1.1 200")
            assert expect in resp
        finally:
            srv.stop()

    def test_client_disconnect_mid_upload_unblocks_handler(self):
        import socket as pysock
        import threading as _threading

        outcome = []
        seen = _threading.Event()

        def upload(frame):
            try:
                while True:
                    piece = frame.body.read(timeout=15)
                    seen.set()
                    if not piece:
                        outcome.append("eof")
                        break
            except IOError as e:
                outcome.append(f"ioerror:{e}")
            return 200, "text/plain", b"x"

        srv = Server()
        srv.add_http_handler("/up", upload, progressive=True)
        assert srv.start(0)
        try:
            conn = pysock.create_connection(("127.0.0.1", srv.port), timeout=10)
            conn.sendall(
                b"POST /up HTTP/1.1\r\nHost: t\r\n"
                b"Transfer-Encoding: chunked\r\n\r\n"
                b"5\r\nhello\r\n"  # one chunk, NO terminator
            )
            assert seen.wait(10)  # handler got the first piece
            conn.close()  # abort mid-upload
            deadline = time.monotonic() + 10
            while not outcome and time.monotonic() < deadline:
                time.sleep(0.02)
            assert outcome and outcome[0].startswith("ioerror:"), outcome
        finally:
            srv.stop()

    def test_pipelined_request_waits_for_progressive_response(self):
        import socket as pysock

        order = []

        def upload(frame):
            body = frame.body.read_all(timeout=20)
            import time as _t

            _t.sleep(0.2)  # let the pipelined GET race if ordering is broken
            order.append("upload")
            return 200, "text/plain", b"U:%d" % len(body)

        def ping(frame):
            order.append("ping")
            return 200, "text/plain", b"PONG"

        srv = Server()
        srv.add_http_handler("/up", upload, progressive=True)
        srv.add_http_handler("/ping", ping)
        assert srv.start(0)
        try:
            conn = pysock.create_connection(("127.0.0.1", srv.port), timeout=10)
            # chunked upload + pipelined GET in one burst
            conn.sendall(
                b"POST /up HTTP/1.1\r\nHost: t\r\n"
                b"Transfer-Encoding: chunked\r\n\r\n"
                b"5\r\nhello\r\n0\r\n\r\n"
                b"GET /ping HTTP/1.1\r\nHost: t\r\nConnection: close\r\n\r\n"
            )
            resp = b""
            while True:
                data = conn.recv(65536)
                if not data:
                    break
                resp += data
            conn.close()
            # responses in request order: upload's first, then the ping
            u = resp.find(b"U:5")
            p = resp.find(b"PONG")
            assert u >= 0 and p >= 0 and u < p, resp[:200]
        finally:
            srv.stop()


class TestRestfulMappings:
    """ServiceOptions.restful_mappings (server.h:255-260, restful.cpp):
    methods exposed on custom paths, wildcards included."""

    @pytest.fixture
    def restful_server(self):
        srv = Server()
        srv.add_service(
            "media",
            {
                "play": lambda cntl, req: b"play:" + req,
                "stat": lambda cntl, req: b"stat",
            },
            restful_mappings="/v1/play => play, *.flv => play, "
                             "/exact/stat => stat",
        )
        assert srv.start(0)
        yield srv
        srv.stop()

    def test_exact_path(self, restful_server):
        status, _, body = fetch(
            restful_server, "/v1/play", method="POST", body=b"X"
        )
        assert status == 200 and body == b"play:X"

    def test_wildcard_suffix(self, restful_server):
        status, _, body = fetch(
            restful_server, "/live/stream123.flv", method="POST", body=b"F"
        )
        assert status == 200 and body == b"play:F"

    def test_no_match_404(self, restful_server):
        status, _, _ = fetch(restful_server, "/v2/play", method="POST")
        assert status == 404

    def test_gateway_route_still_works(self, restful_server):
        status, _, body = fetch(
            restful_server, "/media/stat", method="POST", body=b""
        )
        assert status == 200 and body == b"stat"

    def test_bad_mappings_rejected(self):
        srv = Server()
        with pytest.raises(ValueError):
            srv.add_service(
                "x", {"m": lambda c, r: b""}, restful_mappings="/a -> m"
            )
        with pytest.raises(ValueError):
            srv.add_service(
                "y", {"m": lambda c, r: b""}, restful_mappings="/a => nope"
            )
        with pytest.raises(ValueError):
            srv.add_service(
                "z", {"m": lambda c, r: b""}, restful_mappings="/a/*/b/* => m"
            )

    def test_failed_registration_leaves_nothing_behind(self):
        # a bad mapping must not leave methods or earlier pairs registered
        srv = Server()
        with pytest.raises(ValueError):
            srv.add_service(
                "p",
                {"play": lambda c, r: b""},
                restful_mappings="/ok => play, /bad => nope",
            )
        assert not srv._restful
        assert "p.play" not in srv._methods
        # the fixed retry registers cleanly
        srv.add_service(
            "p", {"play": lambda c, r: b""}, restful_mappings="/ok => play"
        )
        assert len(srv._restful) == 1

    def test_duplicate_paths_rejected(self):
        srv = Server()
        srv.add_service(
            "a", {"m": lambda c, r: b""}, restful_mappings="/v1 => m"
        )
        with pytest.raises(ValueError):
            srv.add_service(
                "b", {"n": lambda c, r: b""}, restful_mappings="/v1 => n"
            )


class TestFlagVars:
    def test_flags_mirror_into_vars(self, portal_server):
        """The reference registers every gflag as a bvar (bvar/gflag.cpp):
        /vars shows flag_<name> rows next to the counters."""
        status, _, body = fetch(portal_server, "/vars?prefix=flag_")
        assert status == 200
        text = body.decode()
        assert "flag_max_body_size : " in text
        assert "flag_health_check_interval : " in text
        assert "socket_in_bytes" not in text  # prefix filter still applies
        # the JSON dump serves from the same source: no disagreement
        import json as _json

        status, _, body = fetch(portal_server, "/vars.json?prefix=flag_")
        assert status == 200
        obj = _json.loads(body)
        assert "flag_max_body_size" in obj
