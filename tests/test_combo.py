"""Combo channel tests (reference test/brpc_parallel_channel_unittest.cpp,
brpc_selective_channel_unittest.cpp, brpc_partition_channel_unittest.cpp —
the in-process many-local-servers shape of SURVEY §4)."""

import threading
import time

import pytest

from incubator_brpc_tpu.rpc import (
    CallMapper,
    Channel,
    ParallelChannel,
    PartitionChannel,
    PartitionParser,
    ResponseMerger,
    SelectiveChannel,
    Server,
    SubCall,
)
from incubator_brpc_tpu.utils.status import ErrorCode


def make_server(name: bytes):
    """Echo server that prefixes responses with its name."""
    server = Server()

    def echo(cntl, request):
        return name + b":" + request

    def fail(cntl, request):
        cntl.set_failed(ErrorCode.EINTERNAL, "injected failure")
        return b""

    def slow(cntl, request):
        time.sleep(0.3)
        return name + b":slow"

    server.add_service("svc", {"echo": echo, "fail": fail, "slow": slow})
    assert server.start(0)
    return server


@pytest.fixture
def three_servers():
    servers = [make_server(b"s%d" % i) for i in range(3)]
    yield servers
    for s in servers:
        s.stop()
    for s in servers:
        s.join(timeout=5)


def sub_channel(server):
    ch = Channel()
    assert ch.init(f"127.0.0.1:{server.port}")
    return ch


class TestParallelChannel:
    def test_broadcast_and_merge_in_index_order(self, three_servers):
        pc = ParallelChannel()
        for s in three_servers:
            pc.add_channel(sub_channel(s))
        cntl = pc.call_method("svc", "echo", b"hi")
        assert cntl.ok(), cntl.error_text
        assert cntl.response_payload == b"s0:his1:his2:hi"

    def test_call_mapper_rewrites_and_skips(self, three_servers):
        class Mapper(CallMapper):
            def map(self, i, n, service, method, request):
                if i == 1:
                    return SubCall.skip()
                return SubCall(request=b"%d" % i)

        pc = ParallelChannel()
        for s in three_servers:
            pc.add_channel(sub_channel(s), call_mapper=Mapper())
        cntl = pc.call_method("svc", "echo", b"ignored")
        assert cntl.ok()
        assert cntl.response_payload == b"s0:0s2:2"

    def test_default_fail_limit_tolerates_partial_failure(self, three_servers):
        """Unset fail_limit = ndone: parent succeeds unless ALL fail
        (parallel_channel.cpp:625-627)."""
        class Mapper(CallMapper):
            def map(self, i, n, service, method, request):
                return SubCall(method="fail" if i == 0 else "echo")

        pc = ParallelChannel()
        for s in three_servers:
            pc.add_channel(sub_channel(s), call_mapper=Mapper())
        cntl = pc.call_method("svc", "echo", b"x")
        assert cntl.ok()
        assert cntl.response_payload == b"s1:xs2:x"  # failed sub not merged

    def test_fail_limit_one_fails_fast(self, three_servers):
        class Mapper(CallMapper):
            def map(self, i, n, service, method, request):
                return SubCall(method="fail" if i == 2 else "echo")

        pc = ParallelChannel(fail_limit=1)
        for s in three_servers:
            pc.add_channel(sub_channel(s), call_mapper=Mapper())
        cntl = pc.call_method("svc", "echo", b"x")
        assert cntl.failed()
        assert cntl.error_code == ErrorCode.EINTERNAL

    def test_all_failed_fails_parent(self, three_servers):
        pc = ParallelChannel()
        for s in three_servers:
            pc.add_channel(sub_channel(s))
        cntl = pc.call_method("svc", "fail", b"x")
        assert cntl.failed()

    def test_custom_merger(self, three_servers):
        class Longest(ResponseMerger):
            def merge(self, merged, sub):
                return sub if len(sub) > len(merged) else merged

        pc = ParallelChannel()
        names = [b"a", b"bb", b"c"]
        for s, n in zip(three_servers, names):
            pc.add_channel(sub_channel(s), response_merger=Longest())
        cntl = pc.call_method("svc", "echo", b"zz")
        assert cntl.ok()
        # all three responses are 5 bytes ("sN:zz"); merge keeps the first
        # (index order) since later ones aren't strictly longer
        assert cntl.response_payload == b"s0:zz"

    def test_async_done(self, three_servers):
        pc = ParallelChannel()
        for s in three_servers:
            pc.add_channel(sub_channel(s))
        ev = threading.Event()
        out = {}

        def done(c):
            out["payload"] = c.response_payload
            ev.set()

        pc.call_method("svc", "echo", b"a", done=done)
        assert ev.wait(timeout=5)
        assert out["payload"] == b"s0:as1:as2:a"


class TestSelectiveChannel:
    def test_round_robins_across_channels(self, three_servers):
        sc = SelectiveChannel()
        for s in three_servers:
            sc.add_channel(sub_channel(s))
        seen = set()
        for _ in range(6):
            cntl = sc.call_method("svc", "echo", b"q")
            assert cntl.ok()
            seen.add(cntl.response_payload)
        assert seen == {b"s0:q", b"s1:q", b"s2:q"}

    def test_failover_to_other_replica(self, three_servers):
        """A dead replica is skipped: retries go to different sub-channels
        (selective_channel.cpp retry contract)."""
        dead = Channel()
        # unused port: connect will fail → retriable EFAILEDSOCKET
        assert dead.init("127.0.0.1:1")
        sc = SelectiveChannel(max_retry=2)
        sc.add_channel(dead)
        sc.add_channel(sub_channel(three_servers[0]))
        for _ in range(4):
            cntl = sc.call_method("svc", "echo", b"f")
            assert cntl.ok(), cntl.error_text
            assert cntl.response_payload == b"s0:f"

    def test_application_error_does_not_failover(self, three_servers):
        sc = SelectiveChannel(max_retry=2)
        for s in three_servers:
            sc.add_channel(sub_channel(s))
        cntl = sc.call_method("svc", "fail", b"x")
        assert cntl.failed()
        assert cntl.error_code == ErrorCode.EINTERNAL

    def test_async_done_does_not_block(self, three_servers):
        sc = SelectiveChannel()
        for s in three_servers:
            sc.add_channel(sub_channel(s))
        ev = threading.Event()
        out = {}

        def done(c):
            out["p"] = c.response_payload
            ev.set()

        t0 = time.monotonic()
        sc.call_method("svc", "slow", b"x", done=done)
        assert time.monotonic() - t0 < 0.2  # returned before the 0.3s handler
        assert ev.wait(timeout=5)
        assert out["p"].endswith(b":slow")

    def test_per_call_deadline_covers_all_retries(self):
        """The caller's timeout bounds the WHOLE call, not each attempt
        (controller deadline semantics)."""
        from incubator_brpc_tpu.rpc import Controller

        sc = SelectiveChannel(max_retry=5)
        for port in (1, 2, 3):
            ch = Channel()
            assert ch.init(f"127.0.0.1:{port}")
            sc.add_channel(ch)
        cntl = Controller(timeout_ms=400, max_retry=5)
        t0 = time.monotonic()
        sc.call_method("svc", "echo", b"x", cntl=cntl)
        assert cntl.failed()
        assert time.monotonic() - t0 < 2.0  # not 6 x timeout

    def test_all_dead_fails(self):
        sc = SelectiveChannel(max_retry=3)
        for port in (1, 2):
            ch = Channel()
            assert ch.init(f"127.0.0.1:{port}")
            sc.add_channel(ch)
        cntl = sc.call_method("svc", "echo", b"x")
        assert cntl.failed()


class _ScriptedSub:
    """Stand-in sub-channel whose outcomes are driven by the test: lets the
    health state machine be exercised deterministically (the reference
    tests its SelectiveChannel health path with controllable fake
    SocketIds the same way)."""

    def __init__(self):
        self.healthy = True
        self.calls = 0

    def call_method(self, service, method, request, cntl=None, done=None):
        self.calls += 1
        if self.healthy:
            cntl.response_payload = b"ok:" + request
        else:
            cntl.set_failed(ErrorCode.EFAILEDSOCKET, "scripted transport down")
        if done:
            done(cntl)
        return cntl


class TestSelectiveChannelHealth:
    """The embedded LB integrates health: a sub-channel with consecutive
    transport failures leaves the candidate set until its backed-off
    revive probe (the reference excludes a failed fake Socket until the
    health check revives it, selective_channel.cpp + socket health loop)."""

    def test_downed_sub_is_excluded_until_revive_probe(self):
        a, b = _ScriptedSub(), _ScriptedSub()
        b.healthy = False
        sc = SelectiveChannel(
            max_retry=2, lb_name="rr",
            health_check_fails=2, health_check_interval_s=0.3,
        )
        sc.add_channel(a)
        sc.add_channel(b)
        # drive calls: b fails its first attempts, hits the streak
        # threshold, and is downed; every call still succeeds via a
        for _ in range(10):
            cntl = sc.call_method("s", "m", b"x")
            assert cntl.ok(), cntl.error_text
        health = {h["index"]: h for h in sc.health()}
        assert health[1]["down"], health
        b_calls_when_downed = b.calls
        # b is OUT of the candidate set: further traffic never touches it
        for _ in range(10):
            assert sc.call_method("s", "m", b"x").ok()
        assert b.calls == b_calls_when_downed, "downed sub still picked"
        # after the interval, the next call probes b in place; still dead
        # -> downed again with doubled backoff, traffic stays on a
        time.sleep(0.35)
        for _ in range(6):
            assert sc.call_method("s", "m", b"x").ok()
        assert b.calls == b_calls_when_downed + 1, "revive probe count"
        # now b recovers; at the next revive probe it serves again and is
        # restored as a full candidate (streak reset, backoff reset)
        b.healthy = True
        time.sleep(0.65)  # doubled backoff
        for _ in range(8):
            assert sc.call_method("s", "m", b"x").ok()
        health = {h["index"]: h for h in sc.health()}
        assert not health[1]["down"], health
        assert b.calls > b_calls_when_downed + 1, "recovered sub not reused"

    def test_all_down_still_probes_rather_than_failing(self):
        a = _ScriptedSub()
        a.healthy = False
        sc = SelectiveChannel(
            max_retry=1, lb_name="rr",
            health_check_fails=1, health_check_interval_s=5.0,
        )
        sc.add_channel(a)
        # first call downs it; second call has NO healthy candidate — the
        # degraded path probes the downed sub instead of failing without
        # an attempt
        assert sc.call_method("s", "m", b"x").failed()
        calls_before = a.calls
        cntl = sc.call_method("s", "m", b"x")
        assert cntl.failed()
        assert a.calls > calls_before, "no probe attempted when all down"

    def test_real_server_outage_shifts_traffic_off_the_replica(self):
        """Integration shape: one replica's server dies mid-traffic; the
        health gate takes it out of rotation (not merely per-call retry),
        and throughput continues on the survivor."""
        alive = make_server(b"alive")
        dying = make_server(b"dying")
        sc = SelectiveChannel(
            max_retry=2, lb_name="rr",
            health_check_fails=2, health_check_interval_s=30.0,
        )
        for srv in (alive, dying):
            sc.add_channel(sub_channel(srv))
        try:
            for _ in range(4):
                assert sc.call_method("svc", "echo", b"w").ok()
            dying.stop()
            dying.join(timeout=5)
            # the first couple of calls may pay the failed attempt; once
            # the streak downs the replica, calls go straight to alive
            for _ in range(8):
                cntl = sc.call_method("svc", "echo", b"w")
                assert cntl.ok(), cntl.error_text
                assert cntl.response_payload == b"alive:w"
            health = {h["index"]: h for h in sc.health()}
            assert health[1]["down"], health
        finally:
            alive.stop()
            alive.join(timeout=5)


class TestNamingTagDiff:
    def test_tag_change_is_remove_then_add(self, tmp_path):
        """A tag-only change must reach observers as remove-then-add so
        tag-blind LBs keep the server (reference ServerNode tag compare)."""
        from incubator_brpc_tpu.naming import NamingServiceThread

        f = tmp_path / "servers"
        f.write_text("127.0.0.1:7001 0/2\n")
        nst = NamingServiceThread(f"file://{f}")
        nst.stop()  # no timer; we drive _refresh by hand
        events = []

        class Obs:
            def add_server(self, ep):
                events.append(("add", ep.port, ep.tag))

            def remove_server(self, ep):
                events.append(("rm", ep.port, ep.tag))

        nst._refresh()
        nst.add_observer(Obs())
        f.write_text("127.0.0.1:7001 1/2\n")
        nst._refresh()
        assert events == [
            ("add", 7001, "0/2"),  # add_observer replay
            ("rm", 7001, "0/2"),
            ("add", 7001, "1/2"),
        ]

    def test_one_address_two_tags_both_tracked(self, tmp_path):
        from incubator_brpc_tpu.naming import NamingServiceThread

        f = tmp_path / "servers"
        f.write_text("127.0.0.1:7002 0/2\n127.0.0.1:7002 1/2\n")
        nst = NamingServiceThread(f"file://{f}")
        nst.stop()
        nst._refresh()
        assert {(ep.port, ep.tag) for ep in nst.servers()} == {
            (7002, "0/2"),
            (7002, "1/2"),
        }
        removed = []

        class Obs:
            def add_server(self, ep):
                pass

            def remove_server(self, ep):
                removed.append(ep.tag)

        nst.add_observer(Obs())
        f.write_text("\n")
        nst._refresh()
        assert sorted(removed) == ["0/2", "1/2"]


class TestPartitionChannel:
    def test_parser(self):
        p = PartitionParser()
        assert p.parse("0/3") == (0, 3)
        assert p.parse("2/3") == (2, 3)
        assert p.parse("3/3") is None
        assert p.parse("junk") is None
        assert p.parse("") is None

    def test_fanout_across_partitions(self, three_servers):
        """Each partition's sub-channel only sees its tagged servers; the
        call fans out across partitions and merges."""
        url = "list://" + ",".join(
            f"127.0.0.1:{s.port} {i}/3" for i, s in enumerate(three_servers)
        )
        pc = PartitionChannel()
        assert pc.init(url, partition_count=3)
        cntl = pc.call_method("svc", "echo", b"p")
        assert cntl.ok(), cntl.error_text
        assert cntl.response_payload == b"s0:ps1:ps2:p"
        pc.stop()

    def test_untagged_servers_excluded(self, three_servers):
        # only partitions 0 and 1 are tagged; server 2 has a foreign tag
        url = "list://" + ",".join(
            [
                f"127.0.0.1:{three_servers[0].port} 0/2",
                f"127.0.0.1:{three_servers[1].port} 1/2",
                f"127.0.0.1:{three_servers[2].port} other",
            ]
        )
        pc = PartitionChannel()
        assert pc.init(url, partition_count=2)
        cntl = pc.call_method("svc", "echo", b"u")
        assert cntl.ok()
        assert cntl.response_payload == b"s0:us1:u"
        pc.stop()

    def test_empty_partition_fails_sub_call(self, three_servers):
        """A partition with no servers fails its sub-call; default
        fail_limit still lets the others succeed."""
        url = "list://" + ",".join(
            [
                f"127.0.0.1:{three_servers[0].port} 0/2",
                # partition 1 is empty
            ]
        )
        pc = PartitionChannel()
        assert pc.init(url, partition_count=2)
        cntl = pc.call_method("svc", "echo", b"e")
        assert cntl.ok(), cntl.error_text
        assert cntl.response_payload == b"s0:e"
        pc.stop()


class TestSelectiveChannelEmbeddedLB:
    def test_la_lb_prefers_the_fast_replica(self):
        # two replicas, one slow: the embedded locality-aware LB should
        # shift traffic to the fast one (the reference's embedded-LB
        # contract over fake SocketIds, selective_channel.cpp)
        import time as _time

        fast = Server()
        fast.add_service("s", {"m": lambda cntl, req: b"fast"})
        assert fast.start(0)
        slow = Server()

        def slow_m(cntl, req):
            _time.sleep(0.05)
            return b"slow"

        slow.add_service("s", {"m": slow_m})
        assert slow.start(0)
        try:
            sc = SelectiveChannel(lb_name="la")
            for srv in (fast, slow):
                ch = Channel()
                assert ch.init(f"127.0.0.1:{srv.port}")
                sc.add_channel(ch)
            results = []
            for _ in range(30):
                c = sc.call_method("s", "m", b"")
                assert c.ok(), c.error_text
                results.append(c.response_payload)
            # after warmup the LA scheduler should strongly prefer fast
            tail = results[10:]
            assert tail.count(b"fast") > tail.count(b"slow"), tail
        finally:
            fast.stop()
            fast.join(timeout=5)
            slow.stop()
            slow.join(timeout=5)

    def test_failed_replica_excluded_then_recovers_selection(self):
        alive = Server()
        alive.add_service("s", {"m": lambda cntl, req: b"ok"})
        assert alive.start(0)
        dead = Server()
        dead.add_service("s", {"m": lambda cntl, req: b"dead"})
        assert dead.start(0)
        dead_port = dead.port
        dead.stop()
        dead.join(timeout=5)
        try:
            sc = SelectiveChannel(max_retry=2, lb_name="rr")
            for target in (f"127.0.0.1:{dead_port}", f"127.0.0.1:{alive.port}"):
                ch = Channel()
                assert ch.init(target)
                sc.add_channel(ch)
            for _ in range(4):
                c = sc.call_method("s", "m", b"")
                assert c.ok(), c.error_text
                assert c.response_payload == b"ok"
        finally:
            alive.stop()
            alive.join(timeout=5)
