#!/usr/bin/env python
"""cancel_echo — cancel an in-flight RPC from another thread (reference
example/cancel_c++: brpc::StartCancel(call_id) fails the call with
ECANCELED; the done callback still runs exactly once).

Demo: a slow server (0.8 s handler), an async call cancelled after 50 ms
— the caller gets ECANCELED in ~50 ms, not at the handler's pace — then a
second call left alone completes normally on the same channel.
"""

import sys
import threading
import time

sys.path.insert(0, ".")

from incubator_brpc_tpu.rpc import Channel, Controller, Server  # noqa: E402
from incubator_brpc_tpu.utils.status import ErrorCode  # noqa: E402


def main() -> None:
    server = Server()

    def slow_echo(cntl, request: bytes) -> bytes:
        time.sleep(0.8)
        return b"late:" + request

    server.add_service("Echo", {"Echo": slow_echo})
    assert server.start(0)

    ch = Channel()
    assert ch.init(f"127.0.0.1:{server.port}")

    done = threading.Event()
    out = {}

    def on_done(cntl):
        out["code"] = cntl.error_code
        out["elapsed_ms"] = (time.monotonic() - t0) * 1e3
        done.set()

    t0 = time.monotonic()
    cntl = Controller(timeout_ms=10000)
    ch.call_method("Echo", "Echo", b"doomed", cntl=cntl, done=on_done)
    time.sleep(0.05)
    cntl.start_cancel()  # any thread may cancel by the call's id
    assert done.wait(5)
    assert out["code"] == ErrorCode.ECANCELED, out
    print(
        f"cancelled call returned ECANCELED after {out['elapsed_ms']:.0f} ms "
        f"(handler runs 800 ms)"
    )

    c2 = ch.call_method("Echo", "Echo", b"patient", cntl=Controller(timeout_ms=10000))
    assert c2.ok(), c2.error_text
    print(f"uncancelled call completed: {c2.response_payload.decode()}")

    server.stop()
    server.join(timeout=10)
    print("cancel demo ok")


if __name__ == "__main__":
    main()
