#!/usr/bin/env python
"""cache_clients — the ecosystem cache clients against in-process mock
servers (reference example/redis_c++ and example/memcache_c++): a
pipelined RESP client with AUTH, and the binary-protocol memcache client
with SASL PLAIN — both over the same Socket stack as every RPC.

Run:  python examples/cache_clients.py
"""

import sys

sys.path.insert(0, ".")

from incubator_brpc_tpu.protocol.memcache_binary import (  # noqa: E402
    MemcacheBinaryClient,
    MockMemcacheBinaryServer,
)
from incubator_brpc_tpu.protocol.resp import (  # noqa: E402
    MockRedisServer,
    RedisClient,
)


def main() -> None:
    rsrv = MockRedisServer(password="hunter2")
    assert rsrv.start()
    r = RedisClient(f"127.0.0.1:{rsrv.port}", password="hunter2")
    r.execute("SET", "greeting", "hello")
    replies = r.pipeline([("GET", "greeting"), ("INCR", "visits"),
                          ("INCR", "visits")])
    print(f"redis: GET greeting={replies[0]!r}, visits={replies[2]}")
    r.close()
    rsrv.stop()

    msrv = MockMemcacheBinaryServer(password="s3cret")
    assert msrv.start()
    m = MemcacheBinaryClient(f"127.0.0.1:{msrv.port}", password="s3cret")
    m.set("k", b"binary-wire", flags=7)
    m.add("counter", b"41")
    m.incr("counter")
    m.incr("counter")
    print(f"memcache(binary): k={m.get('k')!r}, "
          f"counter={m.get('counter')!r}, version={m.version()}")
    m.close()
    msrv.stop()


if __name__ == "__main__":
    main()
