#!/usr/bin/env python
"""naming_failover — naming + LB + health-driven failover (reference
example/dynamic_partition_echo_c++'s naming shape + the ExcludedServers /
health-check machinery): three servers behind a list:// naming target and
an rr balancer; one dies mid-traffic and calls keep succeeding on the
survivors without a failed request reaching the user.

Run:  python examples/naming_failover.py
"""

import sys

sys.path.insert(0, ".")

from incubator_brpc_tpu.rpc import Channel, ChannelOptions, Server  # noqa: E402


def start_server(tag: str) -> Server:
    server = Server()
    server.add_service(
        "EchoService", {"Echo": lambda cntl, req, t=tag: t.encode() + b":" + req}
    )
    assert server.start(0)
    return server


def main() -> None:
    servers = [start_server(f"s{i}") for i in range(3)]
    url = "list://" + ",".join(f"127.0.0.1:{s.port}" for s in servers)
    ch = Channel()
    assert ch.init(url, "rr", options=ChannelOptions(timeout_ms=5000))

    hits = set()
    for _ in range(6):
        cntl = ch.call_method("EchoService", "Echo", b"ping")
        assert cntl.ok(), cntl.error_text
        hits.add(cntl.response_payload.split(b":")[0].decode())
    print(f"round-robin reached: {sorted(hits)}")

    victim = servers.pop()
    victim.stop()
    print("killed one server mid-traffic")

    survivors = set()
    for _ in range(12):
        cntl = ch.call_method("EchoService", "Echo", b"ping")
        assert cntl.ok(), f"call failed after server death: {cntl.error_text}"
        survivors.add(cntl.response_payload.split(b":")[0].decode())
    print(f"all calls kept succeeding; traffic now on: {sorted(survivors)}")

    for s in servers:
        s.stop()


if __name__ == "__main__":
    main()
