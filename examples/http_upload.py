#!/usr/bin/env python
"""http_upload — unbounded chunked uploads and progressive bodies
(reference http_c++ example + ProgressiveReader/ProgressiveAttachment):

- a 5 MiB chunked upload reassembles server-side (stateful dechunking
  across cut windows, far beyond the 64 KiB peek window);
- a progressive route consumes the body WHILE it uploads (the handler
  sees a ProgressiveReader), then streams its response back chunked.

Run:  python examples/http_upload.py
"""

import hashlib
import socket
import sys

sys.path.insert(0, ".")

from incubator_brpc_tpu.rpc import Server  # noqa: E402


def main() -> None:
    def buffered(frame):  # ordinary route: body arrives complete
        digest = hashlib.sha1(frame.body).hexdigest()
        return 200, "text/plain", f"{len(frame.body)}:{digest}".encode()

    def streaming(frame):  # progressive route: body still arriving
        h = hashlib.sha1()
        n = 0
        while True:
            piece = frame.body.read(timeout=30)
            if not piece:
                break
            h.update(piece)
            n += len(piece)

        def respond():  # progressive response: chunked, unbounded
            yield f"consumed {n} bytes while uploading\n".encode()
            yield f"sha1 {h.hexdigest()}\n".encode()

        return 200, "text/plain", respond()

    server = Server()
    server.add_http_handler("/upload", buffered)
    server.add_http_handler("/stream-upload", streaming, progressive=True)
    assert server.start(0)
    print(f"upload server on 127.0.0.1:{server.port}")

    blob = bytes(range(256)) * 4096 * 5  # 5 MiB
    want = hashlib.sha1(blob).hexdigest()

    def post_chunked(path: str) -> bytes:
        conn = socket.create_connection(("127.0.0.1", server.port), timeout=30)
        conn.sendall(
            f"POST {path} HTTP/1.1\r\nHost: demo\r\n"
            "Transfer-Encoding: chunked\r\nConnection: close\r\n\r\n".encode()
        )
        for i in range(0, len(blob), 100_000):
            c = blob[i : i + 100_000]
            conn.sendall(b"%x\r\n%s\r\n" % (len(c), c))
        conn.sendall(b"0\r\n\r\n")
        out = b""
        while True:
            data = conn.recv(65536)
            if not data:
                break
            out += data
        conn.close()
        return out

    resp = post_chunked("/upload")
    assert f"{len(blob)}:{want}".encode() in resp, resp[:200]
    print(f"buffered upload ok: {len(blob)} bytes, sha1 verified")

    resp = post_chunked("/stream-upload")
    assert f"sha1 {want}".encode() in resp, resp[:200]
    print("progressive upload ok: handler consumed the body mid-flight "
          "and streamed its response")
    server.stop()


if __name__ == "__main__":
    main()
