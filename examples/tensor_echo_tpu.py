#!/usr/bin/env python
"""tensor_echo_tpu — the transport=tpu flagship pair (the analog of
reference example/rdma_performance): an RPC server whose echo method runs
as ONE fused XLA computation on the TPU (parse→verify→dispatch→respond in
HBM), fronted by the ordinary RPC plane.
Run: python examples/tensor_echo_tpu.py
"""

import sys

sys.path.insert(0, ".")

from incubator_brpc_tpu.rpc import Channel, Controller, Server  # noqa: E402
from incubator_brpc_tpu.transport.device import DeviceEndpoint  # noqa: E402


def main() -> None:
    import jax

    ep = DeviceEndpoint(window_size=8)
    print("device:", ep.device, "window:", ep.window_size)

    server = Server()
    server.add_service("TensorEcho", {"Echo": ep.server_handler()})
    assert server.start(0)

    ch = Channel()
    assert ch.init(f"127.0.0.1:{server.port}")
    # generous timeout: the first call compiles the device program
    cntl = ch.call_method(
        "TensorEcho", "Echo", b"over the PCIe and back",
        cntl=Controller(timeout_ms=120000),
    )
    assert cntl.ok(), cntl.error_text
    print("echoed via HBM:", cntl.response_payload)

    # direct endpoint path (no RPC hop), pipelined through the window
    import numpy as np

    pendings = [
        ep.call_words(np.full(64, i, dtype=np.uint32), correlation_id=i + 1)
        for i in range(8)
    ]
    for i, p in enumerate(pendings):
        assert p.wait(60) and p.error_code == 0
    print("pipelined 8 calls through the credit window, all ok")
    server.stop()


if __name__ == "__main__":
    main()
