#!/usr/bin/env python
"""link_performance — the RDMA parity harness retargeted at device links
(reference example/rdma_performance/client.cpp:30-40: echo with a tunable
attachment size, qps + latency printout, a --use flag flipping the
transport). BASELINE config #5's shape.

Run (self-contained: starts its own server):
    python examples/link_performance.py                      # device links
    python examples/link_performance.py --transport tcp      # host sockets
    python examples/link_performance.py --attachment-kb 32 --threads 4
"""

import argparse
import sys
import threading
import time

sys.path.insert(0, ".")

from incubator_brpc_tpu.bvar import LatencyRecorder  # noqa: E402
from incubator_brpc_tpu.rpc import (  # noqa: E402
    Channel,
    ChannelOptions,
    Controller,
    Server,
    ServerOptions,
)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--transport", choices=("tpu", "tcp"), default="tpu",
                   help="the use_rdma flip: device links vs host sockets")
    p.add_argument("--attachment-kb", type=int, default=4,
                   help="echoed attachment size in KiB (attachment_size)")
    p.add_argument("--threads", type=int, default=2, help="caller threads")
    p.add_argument("--seconds", type=float, default=3.0, help="test_seconds")
    args = p.parse_args(argv)

    def echo(cntl, req):
        cntl.response_attachment = cntl.request_attachment  # echo_attachment
        return req

    server = Server(ServerOptions(usercode_inline=True))
    server.add_service("perf", {"echo": echo})
    assert server.start(0)

    ch = Channel()
    assert ch.init(
        f"127.0.0.1:{server.port}",
        options=ChannelOptions(
            transport=args.transport,
            timeout_ms=120000,
            link_slot_words=64 * 1024,
        ),
    )
    attachment = b"a" * (args.attachment_kb << 10)
    warm = ch.call_method(
        "perf", "echo", b"warm", attachment=attachment,
        cntl=Controller(timeout_ms=120000),
    )
    assert warm.ok(), warm.error_text

    latency = LatencyRecorder(name=None)
    stop_at = time.monotonic() + args.seconds
    totals = {"calls": 0, "bytes": 0, "fail": 0}
    lock = threading.Lock()

    def worker():
        calls = fail = nbytes = 0
        while time.monotonic() < stop_at:
            t0 = time.perf_counter()
            c = ch.call_method(
                "perf", "echo", b"ping", attachment=attachment,
                cntl=Controller(timeout_ms=120000),
            )
            if c.ok():
                calls += 1
                nbytes += 2 * len(attachment)  # echoed both ways
                latency << (time.perf_counter() - t0) * 1e6
            else:
                fail += 1
        with lock:
            totals["calls"] += calls
            totals["bytes"] += nbytes
            totals["fail"] += fail

    threads = [threading.Thread(target=worker) for _ in range(args.threads)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    print(
        f"transport={args.transport} attachment={args.attachment_kb}KiB "
        f"threads={args.threads}: {totals['calls'] / wall:.0f} qps, "
        f"{totals['bytes'] / wall / 1e9:.3f} GB/s, "
        f"p50={latency.latency_percentile(0.5):.0f}us "
        f"p99={latency.latency_percentile(0.99):.0f}us "
        f"fail={totals['fail']}"
    )
    server.stop()


if __name__ == "__main__":
    main()
