#!/usr/bin/env python
"""nshead_extension — a custom protocol built on the nshead framing, the
reference's example/nshead_extension_c++ (+ nshead_pb_extension_c++)
analog: the server registers ONE NsheadService-style handler that speaks
its own body format (here a tiny "OP arg" text protocol), multiplexed on
the same port as every other wire protocol by the registry scan; the
client is a plain socket speaking nshead frames.

Run:  python examples/nshead_extension.py
"""

import socket
import sys

sys.path.insert(0, ".")

from incubator_brpc_tpu.protocol import nshead  # noqa: E402
from incubator_brpc_tpu.rpc import (  # noqa: E402
    Channel,
    Server,
    ServerOptions,
)


def main() -> None:
    # the extension protocol: body = b"<op> <payload>"; the handler picks
    # the op, and head fields (id/log_id) echo back in the response frame
    def extension_service(cntl, head: dict, body: bytes) -> bytes:
        op, _, arg = body.partition(b" ")
        if op == b"REV":
            return arg[::-1]
        if op == b"UPPER":
            return arg.upper()
        cntl.set_failed(1003, f"unknown nshead op {op!r}")
        return b""

    server = Server(
        ServerOptions(usercode_inline=True, nshead_service=extension_service)
    )
    server.add_service("EchoService", {"Echo": lambda cntl, req: req})
    assert server.start(0)
    print(f"nshead extension server on 127.0.0.1:{server.port}")

    def nshead_call(body: bytes, id=7, log_id=99) -> bytes:
        with socket.create_connection(("127.0.0.1", server.port), 5) as c:
            c.sendall(nshead.pack_frame(body, id=id, log_id=log_id))
            buf = b""
            while True:
                chunk = c.recv(4096)
                assert chunk, "server closed mid-frame"
                buf += chunk
                frame, consumed = nshead.try_parse_frame(buf)
                if frame is not None:
                    # the response head echoes the request identity
                    assert frame.head["id"] == id
                    assert frame.head["log_id"] == log_id
                    return frame.payload

    print(f"  REV hello   -> {nshead_call(b'REV hello').decode()}")
    print(f"  UPPER brpc  -> {nshead_call(b'UPPER brpc').decode()}")

    # the SAME port still answers the modern protocols (registry scan)
    ch = Channel()
    assert ch.init(f"127.0.0.1:{server.port}")
    cntl = ch.call_method("EchoService", "Echo", b"still multiplexed")
    assert cntl.ok(), cntl.error_text
    print(f"  tbus_std    -> {cntl.response_payload.decode()}")
    server.stop()


if __name__ == "__main__":
    main()
