#!/usr/bin/env python
"""multi_threaded_echo — N client threads hammering one server, qps per
thread count (reference example/multi_threaded_echo_c++: -thread_num
sync callers sharing one Channel).

Scaling caveat, measured honestly: on a single-core host (the bench
machine: host_cpus=1) the sweep CANNOT rise with threads — every thread
shares the same core, so the curve documents per-call overhead, not
scaling. On a multi-core host the same sweep shows the shared-Channel
fan-out (one socket, FIFO correlation, MPSC write queue) scaling until
the reactor or the GIL saturates.
"""

import os
import sys
import threading
import time

sys.path.insert(0, ".")

from incubator_brpc_tpu.rpc import Channel, ChannelOptions, Controller, Server  # noqa: E402

DURATION_S = 0.5


def sweep(port: int, nthreads: int) -> float:
    ch = Channel()
    assert ch.init(f"127.0.0.1:{port}", options=ChannelOptions(timeout_ms=10000))
    stop = time.monotonic() + DURATION_S
    counts = [0] * nthreads

    def worker(i: int) -> None:
        while time.monotonic() < stop:
            cntl = ch.call_method(
                "Echo", "Echo", b"ping", cntl=Controller(timeout_ms=10000)
            )
            assert cntl.ok(), cntl.error_text
            counts[i] += 1

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(nthreads)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return sum(counts) / (time.monotonic() - t0)


def main() -> None:
    server = Server()
    server.add_service("Echo", {"Echo": lambda cntl, req: req})
    assert server.start(0)
    ncpu = os.cpu_count() or 1
    print(f"multi-threaded echo sweep (host_cpus={ncpu}, "
          f"{DURATION_S}s per point, one shared Channel):")
    results = {}
    for n in (1, 2, 4):
        qps = sweep(server.port, n)
        results[n] = qps
        print(f"  threads={n}: {qps:,.0f} qps")
    if ncpu == 1:
        print("  note: 1-core host — a flat curve is the EXPECTED result "
              "(threads share the core); per-call overhead is the signal")
    server.stop()
    server.join(timeout=10)
    assert all(q > 0 for q in results.values())
    print("sweep ok")


if __name__ == "__main__":
    main()
