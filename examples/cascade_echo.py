#!/usr/bin/env python
"""cascade_echo — a handler that is itself an RPC client (reference
example/cascade_echo_c++: server A's Echo calls server B's Echo before
answering; exercises user-code re-entrancy into the client stack from a
worker fiber, with the deadline budget shared down the chain).

Demo: client -> frontend -> backend; the frontend's handler issues a
nested sync RPC and annotates the reply; a three-deep chain then shows
depth-limited recursion (the reference example's --depth flag).
"""

import sys

sys.path.insert(0, ".")

from incubator_brpc_tpu.rpc import Channel, Controller, Server  # noqa: E402


def start_backend() -> Server:
    server = Server()
    server.add_service("Echo", {"Echo": lambda cntl, req: b"backend(" + req + b")"})
    assert server.start(0)
    return server


def start_frontend(backend_port: int) -> Server:
    downstream = Channel()
    assert downstream.init(f"127.0.0.1:{backend_port}")
    server = Server()

    def echo(cntl, request: bytes) -> bytes:
        # nested sync RPC from inside a handler fiber; give the child the
        # remaining budget, not a fresh one (the reference passes the
        # parent's deadline down)
        sub = downstream.call_method(
            "Echo", "Echo", request, cntl=Controller(timeout_ms=5000)
        )
        if sub.failed():
            cntl.set_failed(sub.error_code, f"downstream: {sub.error_text}")
            return b""
        return b"frontend(" + sub.response_payload + b")"

    server.add_service("Echo", {"Echo": echo})
    assert server.start(0)
    return server


def start_recursive(depth_port_holder) -> Server:
    """One server whose handler calls ITSELF until depth runs out (the
    --depth recursion of the reference example)."""
    server = Server()
    selfchan = Channel()

    def echo(cntl, request: bytes) -> bytes:
        depth = int(request)
        if depth <= 0:
            return b"bottom"
        sub = selfchan.call_method(
            "Recur", "Echo", b"%d" % (depth - 1),
            cntl=Controller(timeout_ms=5000),
        )
        if sub.failed():
            cntl.set_failed(sub.error_code, sub.error_text)
            return b""
        return b"d%d->" % depth + sub.response_payload

    server.add_service("Recur", {"Echo": echo})
    assert server.start(0)
    assert selfchan.init(f"127.0.0.1:{server.port}")
    return server


def main() -> None:
    backend = start_backend()
    frontend = start_frontend(backend.port)
    ch = Channel()
    assert ch.init(f"127.0.0.1:{frontend.port}")
    cntl = ch.call_method("Echo", "Echo", b"hi", cntl=Controller(timeout_ms=10000))
    assert cntl.ok(), cntl.error_text
    assert cntl.response_payload == b"frontend(backend(hi))"
    print(f"two-hop cascade: {cntl.response_payload.decode()}")

    recur = start_recursive(None)
    rch = Channel()
    assert rch.init(f"127.0.0.1:{recur.port}")
    c = rch.call_method("Recur", "Echo", b"4", cntl=Controller(timeout_ms=10000))
    assert c.ok(), c.error_text
    assert c.response_payload == b"d4->d3->d2->d1->bottom"
    print(f"self-cascade depth 4: {c.response_payload.decode()}")

    for s in (frontend, backend, recur):
        s.stop()
        s.join(timeout=10)
    print("cascade demo ok")


if __name__ == "__main__":
    main()
