#!/usr/bin/env python
"""ubrpc_compack — ubrpc (nshead + mcpack-packed body) end to end, the
reference's example/echo_c++_ubrpc_compack analog: a legacy ubrpc client
calls a modern server through the UbrpcServiceAdaptor, request params and
result travel as mcpack maps (the compack role — this build's bridge
speaks mcpack2, the same tagged binary family), and errors propagate in
the ubrpc result envelope.

Run:  python examples/ubrpc_compack.py
"""

import sys

sys.path.insert(0, ".")

from incubator_brpc_tpu.protocol import legacy_pbrpc as lp  # noqa: E402
from incubator_brpc_tpu.protocol import mcpack  # noqa: E402
from incubator_brpc_tpu.rpc import (  # noqa: E402
    Channel,
    ChannelOptions,
    Controller,
    Server,
    ServerOptions,
)


def main() -> None:
    # the adaptor routes nshead+mcpack frames to ordinary (cntl, bytes)
    # handlers; params arrive as the mcpack body
    def add(cntl, req: bytes) -> bytes:
        params = mcpack.loads(req)
        return mcpack.dumps({"sum": params["a"] + params["b"]})

    def div(cntl, req: bytes) -> bytes:
        params = mcpack.loads(req)
        if params["b"] == 0:
            cntl.set_failed(1008, "division by zero")
            return b""
        return mcpack.dumps({"quot": params["a"] // params["b"]})

    server = Server(
        ServerOptions(
            usercode_inline=True, nshead_service=lp.UbrpcServiceAdaptor
        )
    )
    server.add_service("calc", {"add": add, "div": div})
    assert server.start(0)
    print(f"ubrpc (mcpack2) server on 127.0.0.1:{server.port}")

    ch = Channel()
    assert ch.init(
        f"127.0.0.1:{server.port}",
        options=ChannelOptions(protocol="ubrpc_mcpack2", timeout_ms=5000),
    )
    cntl = ch.call_method(
        "calc", "add", mcpack.dumps({"a": 19, "b": 23}),
        cntl=Controller(timeout_ms=5000),
    )
    assert cntl.ok(), cntl.error_text
    print(f"  calc.add(19, 23)  -> {mcpack.loads(cntl.response_payload)}")

    cntl = ch.call_method(
        "calc", "div", mcpack.dumps({"a": 144, "b": 12}),
        cntl=Controller(timeout_ms=5000),
    )
    assert cntl.ok(), cntl.error_text
    print(f"  calc.div(144, 12) -> {mcpack.loads(cntl.response_payload)}")

    # errors ride the ubrpc result envelope back to the caller
    cntl = ch.call_method(
        "calc", "div", mcpack.dumps({"a": 1, "b": 0}),
        cntl=Controller(timeout_ms=5000),
    )
    assert cntl.failed()
    print(f"  calc.div(1, 0)    -> error {cntl.error_code}: {cntl.error_text}")
    server.stop()


if __name__ == "__main__":
    main()
