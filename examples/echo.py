#!/usr/bin/env python
"""echo — the canonical client/server pair (reference example/echo_c++:
EchoService::Echo returns the request, client prints the round trip).

Run server:  python examples/echo.py server [port]
Run client:  python examples/echo.py client <port> [message]
Or demo both in one process:  python examples/echo.py demo
Flip the transport (same service, frames over the device plane — the
reference's use_rdma flip):  python examples/echo.py demo tpu
"""

import sys

sys.path.insert(0, ".")

from incubator_brpc_tpu.rpc import Channel, ChannelOptions, Server  # noqa: E402


def make_server(port: int = 0) -> Server:
    server = Server()

    def echo(cntl, request: bytes) -> bytes:
        # attachment flows back untouched, like the reference example
        cntl.response_attachment = cntl.request_attachment
        return request

    server.add_service("EchoService", {"Echo": echo})
    assert server.start(port)
    print(f"EchoServer listening on {server.listen_endpoint} "
          f"(portal: http://127.0.0.1:{server.port}/status)")
    return server


def run_client(port: int, message: str = "hello world", transport: str = "tcp") -> None:
    ch = Channel()
    opts = ChannelOptions(transport=transport, timeout_ms=60000)
    assert ch.init(f"127.0.0.1:{port}", options=opts)
    cntl = ch.call_method(
        "EchoService", "Echo", message.encode(), attachment=b"piggyback"
    )
    if cntl.failed():
        raise SystemExit(f"RPC failed: {cntl.error_text}")
    via = ""
    if transport == "tpu" and ch._device_sock is not None:
        via = f" via device link {ch._device_sock.link.devices}"
    print(f"response={cntl.response_payload!r} "
          f"attachment={cntl.response_attachment!r} "
          f"latency={cntl.latency_us:.0f}us{via}")


def main() -> None:
    mode = sys.argv[1] if len(sys.argv) > 1 else "demo"
    if mode == "server":
        server = make_server(int(sys.argv[2]) if len(sys.argv) > 2 else 8000)
        try:
            import time

            while True:
                time.sleep(1)
        except KeyboardInterrupt:
            server.stop()
    elif mode == "client":
        run_client(int(sys.argv[2]), *(sys.argv[3:4] or []))
    else:
        transport = sys.argv[2] if len(sys.argv) > 2 else "tcp"
        server = make_server(0)
        run_client(server.port, transport=transport)
        server.stop()


if __name__ == "__main__":
    main()
