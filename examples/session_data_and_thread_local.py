#!/usr/bin/env python
"""session_data_and_thread_local — pooled per-connection and per-thread
user data (reference example/session_data_and_thread_local: a server whose
handlers read MySessionLocalData via cntl->session_local_data() and
MyThreadLocalData via brpc::thread_local_data(), both produced by
factories in ServerOptions and REUSED across connections/requests).

Demo: two clients connect in sequence; the second connection receives the
first one's recycled session object (same id, bumped use-count) — the
pooled-reuse contract. Thread data is created once per worker thread and
shared by every request that thread serves.
"""

import itertools
import sys
import threading

sys.path.insert(0, ".")

from incubator_brpc_tpu.rpc import (  # noqa: E402
    Channel,
    ChannelOptions,
    Server,
    ServerOptions,
    thread_local_data,
)

_session_ids = itertools.count(1)
_thread_ids = itertools.count(1)


class SessionData:
    """Expensive per-session state (the reference example's
    MySessionLocalData)."""

    def __init__(self):
        self.sid = next(_session_ids)
        self.uses = 0


class SessionFactory:
    def create(self):
        return SessionData()

    def destroy(self, obj):
        print(f"session data #{obj.sid} destroyed after {obj.uses} uses")


class ThreadData:
    def __init__(self):
        self.tid = next(_thread_ids)
        self.requests = 0


def main() -> None:
    server = Server(
        ServerOptions(
            session_local_data_factory=SessionFactory(),
            reserved_session_local_data=1,
            thread_local_data_factory=ThreadData,
        )
    )

    def whoami(cntl, request: bytes) -> bytes:
        sd = cntl.session_local_data()
        td = thread_local_data()
        sd.uses += 1
        td.requests += 1
        return (
            f"session={sd.sid} session_uses={sd.uses} "
            f"thread={td.tid} thread_requests={td.requests} "
            f"worker={threading.current_thread().name}"
        ).encode()

    server.add_service("Session", {"WhoAmI": whoami})
    assert server.start(0)

    sessions_seen = []
    for conn in range(2):  # two connections, one after the other
        ch = Channel()
        # short connections: each client call cycle gets its OWN
        # connection, so the second loop demonstrates pool reuse
        assert ch.init(
            f"127.0.0.1:{server.port}",
            options=ChannelOptions(connection_type="short", timeout_ms=10000),
        )
        cntl = ch.call_method("Session", "WhoAmI", b"")
        assert cntl.ok(), cntl.error_text
        print(f"conn {conn}: {cntl.response_payload.decode()}")
        sessions_seen.append(cntl.response_payload.split(b" ")[0])

    server.stop()
    server.join(timeout=10)
    print(f"pooled sessions observed: {sessions_seen}")


if __name__ == "__main__":
    main()
