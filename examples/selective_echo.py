#!/usr/bin/env python
"""selective_echo — cross-cluster failover through a SelectiveChannel
(reference example/selective_echo_c++: sub-channels are schedulable units
inside an embedded load balancer; a degraded cluster loses traffic, a
dead one leaves rotation until it revives).

Demo: two "clusters" (each a sub-channel). Traffic balances; cluster B
is killed mid-stream — after the health threshold its sub-channel leaves
the candidate set (calls stop even ATTEMPTING it); B comes back on the
same port and the backed-off revive probe restores it to rotation.
"""

import sys
import time
from collections import Counter

sys.path.insert(0, ".")

from incubator_brpc_tpu.rpc import Channel, Controller, SelectiveChannel, Server  # noqa: E402


def start_cluster(name: bytes, port: int = 0) -> Server:
    server = Server()
    server.add_service("Echo", {"Echo": lambda cntl, req: name + b":" + req})
    assert server.start(port)
    return server


def drive(sc, n: int) -> Counter:
    hits: Counter = Counter()
    for _ in range(n):
        cntl = sc.call_method("Echo", "Echo", b"q", cntl=Controller(timeout_ms=5000))
        hits[cntl.response_payload.split(b":")[0] if cntl.ok() else b"FAIL"] += 1
    return hits


def main() -> None:
    a = start_cluster(b"clusterA")
    b = start_cluster(b"clusterB")
    b_port = b.port

    sc = SelectiveChannel(
        max_retry=2, lb_name="rr",
        health_check_fails=2, health_check_interval_s=0.5,
    )
    for srv in (a, b):
        ch = Channel()
        assert ch.init(f"127.0.0.1:{srv.port}")
        sc.add_channel(ch)

    print(f"both clusters up: {dict(drive(sc, 10))}")

    b.stop()
    b.join(timeout=5)
    hits = drive(sc, 10)
    print(f"clusterB down:    {dict(hits)}  (no failures — retries + health gate)")
    assert hits[b"FAIL"] == 0 and hits[b"clusterA"] == 10
    health = {h["index"]: h["down"] for h in sc.health()}
    print(f"health view:      {health}")
    assert health[1] is True

    b2 = start_cluster(b"clusterB", b_port)  # same endpoint revives
    time.sleep(1.2)  # past the backed-off revive window
    hits = drive(sc, 12)
    print(f"clusterB revived: {dict(hits)}")
    assert hits[b"clusterB"] > 0, "revive probe never restored traffic"

    for srv in (a, b2):
        srv.stop()
        srv.join(timeout=5)
    print("selective failover demo ok")


if __name__ == "__main__":
    main()
