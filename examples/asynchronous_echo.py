#!/usr/bin/env python
"""asynchronous_echo — async on both ends (reference
example/asynchronous_echo_c++: the client's done-closure runs on
completion instead of blocking; the server's handler finishes later via
the done guard).

Demo: the server parks each request on a timer (no handler thread held,
cntl.set_async + send_response); the client launches a burst of async
calls and collects completions — total wall time ~one response delay,
not burst x delay.
"""

import sys
import threading
import time

sys.path.insert(0, ".")

from incubator_brpc_tpu.rpc import Channel, Controller, Server  # noqa: E402
from incubator_brpc_tpu.runtime.timer_thread import global_timer_thread  # noqa: E402

N = 32
DELAY_S = 0.3


def main() -> None:
    timer = global_timer_thread()
    server = Server()

    def echo_later(cntl, request: bytes):
        # async server side: the handler returns immediately; the response
        # goes out from the timer callback (the reference's done-guard
        # released after a bthread_usleep)
        cntl.set_async()
        timer.schedule(
            lambda: cntl.send_response(b"late:" + request), delay=DELAY_S
        )
        return None

    server.add_service("Echo", {"Echo": echo_later})
    assert server.start(0)

    ch = Channel()
    assert ch.init(f"127.0.0.1:{server.port}")

    done = threading.Event()
    results = []
    lock = threading.Lock()

    def on_done(cntl):
        with lock:
            results.append(cntl.ok())
            if len(results) == N:
                done.set()

    t0 = time.monotonic()
    for i in range(N):
        ch.call_method(
            "Echo", "Echo", b"m%02d" % i,
            cntl=Controller(timeout_ms=10000), done=on_done,
        )
    launched = time.monotonic() - t0
    assert done.wait(10)
    total = time.monotonic() - t0
    assert all(results)
    print(
        f"{N} async calls: launched in {launched*1e3:.0f} ms, all done in "
        f"{total*1e3:.0f} ms (server delay {DELAY_S*1e3:.0f} ms each — "
        f"overlapped, not {N * DELAY_S:.1f} s serial)"
    )
    assert total < N * DELAY_S / 4, "async calls did not overlap"
    server.stop()
    server.join(timeout=10)
    print("asynchronous echo demo ok")


if __name__ == "__main__":
    main()
