#!/usr/bin/env python
"""parallel_echo — scatter/gather over a ParallelChannel (reference
example/parallel_echo_c++): one call fans out to N sub-channels, responses
merge in channel order. Run: python examples/parallel_echo.py
"""

import sys

sys.path.insert(0, ".")

from incubator_brpc_tpu.rpc import Channel, ParallelChannel, Server  # noqa: E402


def main() -> None:
    servers = []
    for i in range(3):
        s = Server()
        s.add_service(
            "EchoService", {"Echo": (lambda c, req, _i=i: b"[replica%d]%s" % (_i, req))}
        )
        assert s.start(0)
        servers.append(s)

    pc = ParallelChannel()  # default fail_limit: succeeds unless ALL fail
    for s in servers:
        ch = Channel()
        assert ch.init(f"127.0.0.1:{s.port}")
        pc.add_channel(ch)

    cntl = pc.call_method("EchoService", "Echo", b"fanout")
    assert cntl.ok(), cntl.error_text
    print(f"merged response: {cntl.response_payload!r}")
    for s in servers:
        s.stop()


if __name__ == "__main__":
    main()
