#!/usr/bin/env python
"""parallel_echo — scatter/gather over a ParallelChannel (reference
example/parallel_echo_c++): one call fans out to N sub-channels, responses
merge in channel order. With enough mesh devices, the second half shows
the ICI collective lowering (BASELINE config #3): the same call over
device links to distinct devices fuses into ONE shard_map all-gather
dispatch — byte-identical to the host fan-out.

Run: python examples/parallel_echo.py
"""

import sys

sys.path.insert(0, ".")

from incubator_brpc_tpu.rpc import (  # noqa: E402
    Channel,
    ChannelOptions,
    ParallelChannel,
    Server,
    ServerOptions,
    device_method,
)


def main() -> None:
    servers = []
    for i in range(3):
        s = Server()
        s.add_service(
            "EchoService", {"Echo": (lambda c, req, _i=i: b"[replica%d]%s" % (_i, req))}
        )
        assert s.start(0)
        servers.append(s)

    pc = ParallelChannel()  # default fail_limit: succeeds unless ALL fail
    for s in servers:
        ch = Channel()
        assert ch.init(f"127.0.0.1:{s.port}")
        pc.add_channel(ch)

    cntl = pc.call_method("EchoService", "Echo", b"fanout")
    assert cntl.ok(), cntl.error_text
    print(f"merged response: {cntl.response_payload!r}")
    for s in servers:
        s.stop()

    # -- the collective lowering (SURVEY §2.5; needs a 4+ device mesh) ----
    import jax

    if len(jax.devices()) < 4:
        print("(single device: the fused-collective half needs a 4+ mesh)")
        return

    def add_one(data, n):  # the device kernel every partition serves
        import jax.numpy as jnp

        return data + jnp.uint8(1), n

    dservers = []
    for i in range(3):
        s = Server(ServerOptions(device_index=i + 1, usercode_inline=True))
        s.add_service("dsvc", {"inc": device_method(add_one, width=256)})
        assert s.start(0)
        dservers.append(s)
    fused = ParallelChannel()
    for s in dservers:
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{s.port}",
            options=ChannelOptions(transport="tpu", timeout_ms=60000),
        )
        fused.add_channel(ch)
    cntl = fused.call_method("dsvc", "inc", b"\x01\x02\x03")
    assert cntl.ok(), cntl.error_text
    print(
        f"fused={getattr(cntl, 'collective_fused', False)} "
        f"merged={cntl.response_payload!r}  "
        "(one shard_map all-gather dispatch, not 3 RPCs)"
    )
    for s in dservers:
        s.stop()


if __name__ == "__main__":
    main()
