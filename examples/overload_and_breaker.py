#!/usr/bin/env python
"""overload_and_breaker — the robustness layer end to end (reference
policy/auto_concurrency_limiter.cpp + circuit_breaker.cpp): a 3-backend
cluster behind a round-robin channel; one backend browns out under
injected faults (deterministic FaultInjector, 50% of its dispatches
fail), the per-node circuit breaker isolates it within its short error
window, client goodput recovers to clean, and when the fault clears the
node revives half-open and takes traffic again. The same run shows a
server shedding an overload flood with ELIMIT under
``max_concurrency="auto"``.

Run:  python examples/overload_and_breaker.py
"""

import sys
import threading
import time

sys.path.insert(0, ".")

from incubator_brpc_tpu.rpc import (  # noqa: E402
    Channel,
    ChannelOptions,
    FaultInjector,
    Server,
    ServerOptions,
)
from incubator_brpc_tpu.utils.flags import set_flag_unchecked  # noqa: E402
from incubator_brpc_tpu.utils.status import ErrorCode  # noqa: E402


def start_backend(tag: str, fault_injector=None) -> Server:
    server = Server(ServerOptions(fault_injector=fault_injector))
    server.add_service(
        "EchoService", {"Echo": lambda cntl, req, t=tag: t.encode() + b":" + req}
    )
    assert server.start(0)
    return server


def error_rate(ch: Channel, n: int) -> float:
    fails = sum(
        1 for _ in range(n)
        if ch.call_method("EchoService", "Echo", b"ping").failed()
    )
    return fails / n


def breaker_demo() -> None:
    # small windows so the demo converges in seconds, not minutes
    set_flag_unchecked("circuit_breaker_short_window_size", 30)
    set_flag_unchecked("circuit_breaker_min_isolation_duration_ms", 500)
    set_flag_unchecked("fault_injection", True)

    healthy = [start_backend("s0"), start_backend("s1")]
    # s2 browns out: every 2nd dispatch fails (counter-based, not random)
    brown = start_backend("s2", FaultInjector(error_rate=0.5))
    backends = healthy + [brown]
    url = "list://" + ",".join(f"127.0.0.1:{s.port}" for s in backends)
    ch = Channel()
    assert ch.init(url, "rr", options=ChannelOptions(max_retry=0, timeout_ms=4000))

    rate = error_rate(ch, 30)
    print(f"brownout: error rate with s2 at 50% injected faults = {rate:.0%}")

    # the breaker trips inside its short window and takes s2 out
    deadline = time.monotonic() + 10
    while not ch._lb.isolated_servers() and time.monotonic() < deadline:
        ch.call_method("EchoService", "Echo", b"ping")
    iso = ch._lb.isolated_servers()
    assert iso and iso[0].port == brown.port, iso
    print(f"breaker isolated 127.0.0.1:{brown.port} "
          f"(state={ch._lb.breaker_states()[f'127.0.0.1:{brown.port}']['state']})")

    rate = error_rate(ch, 30)
    print(f"recovered: error rate with s2 isolated = {rate:.0%}")
    assert rate < 0.02

    # fault clears -> the node revives (half-open) and serves again
    brown.fault_injector = None
    deadline = time.monotonic() + 10
    while ch._lb.isolated_servers() and time.monotonic() < deadline:
        ch.call_method("EchoService", "Echo", b"ping")
        time.sleep(0.05)
    assert not ch._lb.isolated_servers()
    tags = set()
    for _ in range(9):
        c = ch.call_method("EchoService", "Echo", b"ping")
        assert c.ok(), c.error_text
        tags.add(c.response_payload.split(b":")[0].decode())
    print(f"revived: traffic reaches {sorted(tags)} again, zero errors")

    ch._lb.stop()
    for s in backends:
        s.stop()


def auto_limiter_demo() -> None:
    set_flag_unchecked("auto_cl_initial_max_concurrency", 2)
    srv = Server(ServerOptions(max_concurrency="auto"))
    gate = threading.Event()
    srv.add_service(
        "SlowService", {"Work": lambda cntl, req: (gate.wait(3), b"done")[1]}
    )
    assert srv.start(0)
    ch = Channel()
    assert ch.init(
        f"127.0.0.1:{srv.port}",
        options=ChannelOptions(max_retry=0, timeout_ms=5000),
    )
    codes = []

    def caller():
        codes.append(ch.call_method("SlowService", "Work", b"").error_code)

    threads = [threading.Thread(target=caller) for _ in range(6)]
    for t in threads:
        t.start()
    time.sleep(0.3)
    gate.set()
    for t in threads:
        t.join()
    shed = sum(1 for c in codes if c == ErrorCode.ELIMIT)
    print(
        f"auto limiter: 6 concurrent vs adaptive limit "
        f"{srv.max_concurrency} -> {shed} shed with ELIMIT, "
        f"{codes.count(0)} served"
    )
    assert shed > 0
    srv.stop()
    srv.join(5)


def main() -> None:
    try:
        breaker_demo()
        auto_limiter_demo()
    finally:
        # the demo knobs are process-global flags: restore the defaults so
        # an in-process harness (tests/test_examples.py) is unaffected
        set_flag_unchecked("fault_injection", False)
        set_flag_unchecked("circuit_breaker_short_window_size", 1500)
        set_flag_unchecked("circuit_breaker_min_isolation_duration_ms", 100)
        set_flag_unchecked("auto_cl_initial_max_concurrency", 40)
    print("overload_and_breaker: OK")


if __name__ == "__main__":
    main()
