#!/usr/bin/env python
"""partition_echo — sharded service behind one naming entry (reference
example/partition_echo_c++ + dynamic_partition_echo_c++): servers publish
"N/M" partition tags; a PartitionChannel fans a call across all partitions;
a DynamicPartitionChannel weights traffic across coexisting schemes.
Run: python examples/partition_echo.py
"""

import sys

sys.path.insert(0, ".")

from incubator_brpc_tpu.rpc import (  # noqa: E402
    DynamicPartitionChannel,
    PartitionChannel,
    Server,
)


def shard_server(i: int) -> Server:
    s = Server()
    s.add_service(
        "EchoService", {"Echo": (lambda c, req, _i=i: b"[shard%d]%s" % (_i, req))}
    )
    assert s.start(0)
    return s


def main() -> None:
    shards = [shard_server(i) for i in range(3)]
    url = "list://" + ",".join(
        f"127.0.0.1:{s.port} {i}/3" for i, s in enumerate(shards)
    )

    pc = PartitionChannel()
    assert pc.init(url, partition_count=3)
    cntl = pc.call_method("EchoService", "Echo", b"sharded")
    assert cntl.ok(), cntl.error_text
    print("partitioned response:", cntl.response_payload)
    pc.stop()

    # dynamic: a /3 scheme and a /1 scheme coexist mid-repartition
    extra = shard_server(99)
    url2 = url + f",127.0.0.1:{extra.port} 0/1"
    dpc = DynamicPartitionChannel()
    assert dpc.init(url2)
    seen = set()
    for _ in range(12):
        c = dpc.call_method("EchoService", "Echo", b"x")
        assert c.ok(), c.error_text
        seen.add(c.response_payload)
    print("dynamic schemes answered:", sorted(seen))
    dpc.stop()
    for s in shards + [extra]:
        s.stop()

    # -- the same partitioned call over DEVICE LINKS (needs a 4+ mesh):
    # each shard binds its own mesh device; the client holds a star of
    # links through the DeviceLinkMap (the SocketMap analog; SURVEY §2.5's
    # sharded parameter-server shape) ---------------------------------------
    import jax

    if len(jax.devices()) < 4:
        print("(single device: the device-fabric half needs a 4+ mesh)")
        return
    from incubator_brpc_tpu.rpc import ChannelOptions, ServerOptions

    dshards = []
    for i in range(3):
        s = Server(ServerOptions(device_index=i + 1, usercode_inline=True))
        s.add_service(
            "EchoService", {"Echo": (lambda c, req, _i=i: b"[dev%d]%s" % (_i, req))}
        )
        assert s.start(0)
        dshards.append(s)
    durl = "list://" + ",".join(
        f"127.0.0.1:{s.port} {i}/3" for i, s in enumerate(dshards)
    )
    dpc2 = PartitionChannel()
    assert dpc2.init(
        durl,
        partition_count=3,
        options=ChannelOptions(transport="tpu", timeout_ms=60000),
    )
    from incubator_brpc_tpu.rpc import Controller

    # sub-calls inherit the PARENT controller's budget: give the first
    # call room for 3 link handshakes + the first jitted step's compile
    cntl = dpc2.call_method(
        "EchoService", "Echo", b"over-ici", cntl=Controller(timeout_ms=60000)
    )
    assert cntl.ok(), cntl.error_text
    peers = sorted(
        str(sub[0]._device_sock.link.devices[1]) for sub in dpc2._subs
    )
    print(f"device-fabric response: {cntl.response_payload!r}")
    print(f"star fabric peers: {peers}")
    dpc2.stop()
    for s in dshards:
        s.stop()


if __name__ == "__main__":
    main()
