#!/usr/bin/env python
"""rtmp_relay — media relay + FLV dump (reference example/rtmp_c++ and
the rtmp.cpp publish/play machinery): a publisher pushes metadata and
AV frames, a player joins and receives the relay, and the server tees
the stream into an in-memory FLV file.

Run:  python examples/rtmp_relay.py
"""

import io
import sys
import threading

sys.path.insert(0, ".")

from incubator_brpc_tpu.protocol import amf0, flv, rtmp  # noqa: E402
from incubator_brpc_tpu.rpc import Server, ServerOptions  # noqa: E402


def main() -> None:
    sinks = {}

    def sink_factory(name):
        sinks[name] = io.BytesIO()
        return sinks[name]

    server = Server(
        ServerOptions(
            usercode_inline=True,
            rtmp_service=flv.FlvDumpService(sink_factory),
        )
    )
    server.add_service("svc", {"echo": lambda cntl, req: req})
    assert server.start(0)
    print(f"RTMP relay on rtmp://127.0.0.1:{server.port}/live")

    received = []
    got = threading.Event()

    def on_media(msg):
        received.append(msg)
        if len(received) >= 3:
            got.set()

    publisher = rtmp.RtmpClient("127.0.0.1", server.port)
    pub_stream = publisher.create_stream()
    assert pub_stream.publish("studio")

    player = rtmp.RtmpClient("127.0.0.1", server.port)
    play_stream = player.create_stream()
    assert play_stream.play("studio", on_media=on_media)

    pub_stream.send_metadata({"width": 1280.0, "height": 720.0})
    pub_stream.send_audio(0, b"\xaf\x00" + b"aac-config")
    pub_stream.send_video(0, b"\x17\x00" + b"avc-config")
    assert got.wait(10), "player received nothing"
    print(f"player received {len(received)} relayed messages")

    # snapshot the dump BEFORE closing: the service closes its sink when
    # the publisher's stream ends
    import time

    deadline = time.monotonic() + 10
    flv_bytes = b""
    while time.monotonic() < deadline:
        flv_bytes = sinks["studio"].getvalue()
        if len(list(flv.FlvReader(flv_bytes))) >= 3:
            break
        time.sleep(0.05)
    publisher.close()
    player.close()
    tags = list(flv.FlvReader(flv_bytes))
    kinds = {t: 0 for t, _, _ in tags}
    for t, _, _ in tags:
        kinds[t] += 1
    script = next(d for t, _, d in tags if t == flv.TAG_SCRIPT)
    _, meta = amf0.decode_all(script)
    print(f"server dumped {len(tags)} FLV tags {kinds}; "
          f"onMetaData width={meta['width']:.0f}")
    server.stop()


if __name__ == "__main__":
    main()
