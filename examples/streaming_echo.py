#!/usr/bin/env python
"""streaming_echo — bidirectional stream with credit-window flow control
(reference example/streaming_echo_c++): the client opens a stream on an
RPC, pushes messages, the server echoes them back on its half.
Run: python examples/streaming_echo.py
"""

import sys
import threading
import time

sys.path.insert(0, ".")

from incubator_brpc_tpu.rpc import (  # noqa: E402
    Channel,
    Server,
    StreamHandler,
    StreamOptions,
    stream_accept,
    stream_create,
)


def main() -> None:
    server = Server()
    server_streams = {}

    class ServerSide(StreamHandler):
        def on_received_messages(self, stream, messages):
            for m in messages:
                stream.write(b"echo:" + m)  # push back on our half

        def on_closed(self, stream):
            print("[server] stream closed")

    def open_stream(cntl, request):
        s = stream_accept(cntl, StreamOptions(handler=ServerSide()))
        server_streams[s.id] = s
        return b"stream accepted"

    server.add_service("StreamService", {"Open": open_stream})
    assert server.start(0)

    got, done = [], threading.Event()

    class ClientSide(StreamHandler):
        def on_received_messages(self, stream, messages):
            got.extend(messages)
            if len(got) >= 5:
                done.set()

    ch = Channel()
    assert ch.init(f"127.0.0.1:{server.port}")
    s = stream_create(StreamOptions(handler=ClientSide(), max_buf_size=1 << 20))
    cntl = ch.call_method("StreamService", "Open", b"", request_stream=s)
    assert cntl.ok(), cntl.error_text
    assert s.wait_connected(5)

    for i in range(5):
        assert s.write(b"msg-%d" % i) == 0
        time.sleep(0.02)
    assert done.wait(5)
    print("[client] received:", got)
    s.close()
    server.stop()


if __name__ == "__main__":
    main()
