#!/usr/bin/env python
"""dynamic_partition_echo — two partition schemes behind one naming file,
traffic weighted by live capacity (reference example/
dynamic_partition_echo_c++: servers tagged "N/3" and "N/4" coexist while a
fleet re-partitions; DynamicPartitionChannel routes each call to ONE
scheme — probability ∝ replicas/partitions — then fans out across that
scheme's partitions).

Demo: start a 2-partition generation, drive traffic; bring up a
3-partition generation in the SAME naming file (a rolling re-partition),
drive more traffic and watch calls land on both schemes; retire the old
generation and see every call take the new one.
"""

import sys
import tempfile
import time
from collections import Counter

sys.path.insert(0, ".")

from incubator_brpc_tpu.rpc import (  # noqa: E402
    ChannelOptions,
    Controller,
    DynamicPartitionChannel,
    Server,
)


def start_partition_server(index: int, count: int) -> Server:
    server = Server()

    def get(cntl, request: bytes) -> bytes:
        return f"{index}/{count}:".encode() + request

    server.add_service("Echo", {"Get": get})
    assert server.start(0)
    return server


def scheme_of(payload: bytes) -> int:
    """A response like b'0/2:x1/2:x' came from the 2-partition scheme."""
    return int(payload.split(b":", 1)[0].split(b"/")[1])


def drive(ch, n: int) -> Counter:
    hits: Counter = Counter()
    for i in range(n):
        cntl = ch.call_method(
            "Echo", "Get", b"q", cntl=Controller(timeout_ms=10000)
        )
        assert cntl.ok(), cntl.error_text
        hits[scheme_of(cntl.response_payload)] += 1
    return hits


def main() -> None:
    gen2 = [start_partition_server(i, 2) for i in range(2)]
    naming = tempfile.NamedTemporaryFile("w", suffix=".servers", delete=False)

    def publish(servers_with_schemes) -> None:
        lines = [
            f"127.0.0.1:{srv.port} {i}/{cnt}"
            for srv, i, cnt in servers_with_schemes
        ]
        with open(naming.name, "w") as f:
            f.write("\n".join(lines) + "\n")

    publish([(s, i, 2) for i, s in enumerate(gen2)])
    ch = DynamicPartitionChannel()
    assert ch.init(
        f"file://{naming.name}", options=ChannelOptions(timeout_ms=10000)
    )
    time.sleep(1.5)  # let the naming thread poll the file (1 Hz)

    print("phase 1 — only the 2-partition generation:")
    print(f"  scheme hits: {dict(drive(ch, 20))}")

    # rolling re-partition: the 3-partition generation joins the SAME file
    gen3 = [start_partition_server(i, 3) for i in range(3)]
    publish(
        [(s, i, 2) for i, s in enumerate(gen2)]
        + [(s, i, 3) for i, s in enumerate(gen3)]
    )
    time.sleep(1.5)
    print("phase 2 — both generations live (traffic splits by capacity):")
    hits = drive(ch, 60)
    print(f"  scheme hits: {dict(hits)}")
    assert set(hits) == {2, 3}, "both schemes should take traffic"

    # retire the old generation
    publish([(s, i, 3) for i, s in enumerate(gen3)])
    time.sleep(1.5)
    print("phase 3 — old generation retired:")
    hits = drive(ch, 20)
    print(f"  scheme hits: {dict(hits)}")
    assert set(hits) == {3}, "retired scheme still taking traffic"

    ch.stop()
    for s in gen2 + gen3:
        s.stop()
        s.join(timeout=5)
    print("dynamic re-partition demo ok")


if __name__ == "__main__":
    main()
