#!/usr/bin/env python
"""backup_request — straggler mitigation (reference
example/backup_request_c++): a duplicate request fires at backup_request_ms;
the faster replica wins. Run: python examples/backup_request.py
"""

import sys
import time

sys.path.insert(0, ".")

from incubator_brpc_tpu.rpc import Channel, ChannelOptions, Server  # noqa: E402


def main() -> None:
    slow, fast = Server(), Server()

    def slow_echo(cntl, req):
        time.sleep(1.0)
        return b"slow:" + req

    slow.add_service("EchoService", {"Echo": slow_echo})
    fast.add_service("EchoService", {"Echo": lambda c, req: b"fast:" + req})
    assert slow.start(0) and fast.start(0)

    ch = Channel()
    # list naming + rr: the first attempt may land on the slow replica; the
    # backup fires at 100ms and the retry excludes the slow socket
    assert ch.init(
        f"list://127.0.0.1:{slow.port},127.0.0.1:{fast.port}",
        "rr",
        options=ChannelOptions(timeout_ms=5000, backup_request_ms=100),
    )
    t0 = time.monotonic()
    cntl = ch.call_method("EchoService", "Echo", b"hurry")
    dt = (time.monotonic() - t0) * 1e3
    assert cntl.ok(), cntl.error_text
    print(f"winner: {cntl.response_payload!r} after {dt:.0f}ms "
          f"(slow replica would have taken 1000ms)")
    slow.stop()
    fast.stop()


if __name__ == "__main__":
    main()
