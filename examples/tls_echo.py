#!/usr/bin/env python
"""tls_echo — encrypted echo (reference example/http_c++ ssl options /
ChannelOptions.ssl_options): the server encrypts every accepted
connection; the client verifies the server certificate. The demo certs
live next to this file (like the reference example ships cert.pem).

Run:  python examples/tls_echo.py
"""

import pathlib
import ssl
import sys

sys.path.insert(0, ".")

from incubator_brpc_tpu.rpc import (  # noqa: E402
    Channel,
    ChannelOptions,
    Server,
    ServerOptions,
)

HERE = pathlib.Path(__file__).parent


def main() -> None:
    server_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    server_ctx.load_cert_chain(HERE / "cert.pem", HERE / "key.pem")
    server = Server(ServerOptions(ssl_context=server_ctx))
    server.add_service("EchoService", {"Echo": lambda cntl, req: req})
    assert server.start(0)
    print(f"TLS EchoServer on 127.0.0.1:{server.port}")

    client_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    client_ctx.load_verify_locations(HERE / "cert.pem")
    client_ctx.check_hostname = False  # demo cert is CN=localhost, target is the IP
    client_ctx.verify_mode = ssl.CERT_REQUIRED
    ch = Channel()
    assert ch.init(
        f"127.0.0.1:{server.port}",
        options=ChannelOptions(ssl_context=client_ctx),
    )
    cntl = ch.call_method("EchoService", "Echo", b"over-tls")
    assert cntl.ok(), cntl.error_text
    print(f"response={cntl.response_payload!r} "
          f"(cipher negotiated, cert verified)")
    server.stop()


if __name__ == "__main__":
    main()
