#!/usr/bin/env python
"""tls_echo — encrypted echo (reference example/http_c++ ssl options /
ChannelOptions.ssl_options): the server encrypts every accepted
connection; the client verifies the server certificate. A throwaway
key/cert pair is generated at runtime — never commit private keys next
to example code.

Run:  python examples/tls_echo.py
"""

import ssl
import subprocess
import sys
import tempfile

sys.path.insert(0, ".")

from incubator_brpc_tpu.rpc import (  # noqa: E402
    Channel,
    ChannelOptions,
    Server,
    ServerOptions,
)


def make_throwaway_cert(tmpdir: str) -> tuple:
    """Self-signed localhost cert valid for one day, in a temp dir."""
    cert, key = f"{tmpdir}/cert.pem", f"{tmpdir}/key.pem"
    subprocess.run(
        [
            "openssl", "req", "-x509", "-newkey", "rsa:2048",
            "-keyout", key, "-out", cert, "-days", "1", "-nodes",
            "-subj", "/CN=localhost",
        ],
        check=True,
        capture_output=True,
    )
    return cert, key


def main() -> None:
    tmp = tempfile.TemporaryDirectory(prefix="tls_echo_")
    cert, key = make_throwaway_cert(tmp.name)
    server_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    server_ctx.load_cert_chain(cert, key)
    server = Server(ServerOptions(ssl_context=server_ctx))
    server.add_service("EchoService", {"Echo": lambda cntl, req: req})
    assert server.start(0)
    print(f"TLS EchoServer on 127.0.0.1:{server.port}")

    client_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    client_ctx.load_verify_locations(cert)
    client_ctx.check_hostname = False  # demo cert is CN=localhost, target is the IP
    client_ctx.verify_mode = ssl.CERT_REQUIRED
    ch = Channel()
    assert ch.init(
        f"127.0.0.1:{server.port}",
        options=ChannelOptions(ssl_context=client_ctx),
    )
    cntl = ch.call_method("EchoService", "Echo", b"over-tls")
    assert cntl.ok(), cntl.error_text
    print(f"response={cntl.response_payload!r} "
          f"(cipher negotiated, cert verified)")
    server.stop()
    tmp.cleanup()  # remove the throwaway key material promptly


if __name__ == "__main__":
    main()
