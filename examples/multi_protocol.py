#!/usr/bin/env python
"""multi_protocol — ONE server port answering five wire protocols
(reference example/multi_threaded_echo_c++ + the per-connection protocol
scan, global.cpp:364-525): tbus_std, baidu_std ("PRPC"), hulu_pbrpc,
sofa_pbrpc, and the HTTP gateway, all multiplexed by the registry scan.

Run:  python examples/multi_protocol.py
"""

import sys
import urllib.request

sys.path.insert(0, ".")

from incubator_brpc_tpu.rpc import (  # noqa: E402
    Channel,
    ChannelOptions,
    Server,
    ServerOptions,
)


def main() -> None:
    server = Server(ServerOptions(usercode_inline=True))
    server.add_service("EchoService", {"Echo": lambda cntl, req: req})
    assert server.start(0)
    port = server.port
    print(f"one port, many protocols: 127.0.0.1:{port}")

    for proto in ("tbus_std", "baidu_std", "hulu_pbrpc", "sofa_pbrpc"):
        ch = Channel()
        assert ch.init(
            f"127.0.0.1:{port}", options=ChannelOptions(protocol=proto)
        )
        cntl = ch.call_method("EchoService", "Echo", proto.encode())
        assert cntl.ok(), cntl.error_text
        print(f"  {proto:12s} -> {cntl.response_payload.decode()}")

    # the same port serves the HTTP portal + gateway
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/health", timeout=10
    ).read()
    print(f"  http         -> GET /health = {body.decode().strip()!r}")
    server.stop()


if __name__ == "__main__":
    main()
