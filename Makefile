# Repo-level developer entry points.
#
#   make lint  — fabriclint: FFI signature cross-check, hot-path purity,
#                flag/bvar registry lint, callback keepalive, tb_* return
#                audit (tools/fabriclint; also runs inside tier-1 via
#                tests/test_static_analysis.py)
#   make san   — sanitizer harness: ASAN+UBSAN over the native test
#                subset, TSAN over the telemetry-ring stress (probe-gated:
#                skips cleanly where the toolchain lacks support)
#   make native — the plain native runtime build (src/build/libtbutil.so)
#   make test  — the tier-1 test suite
#
# docs/ANALYSIS.md documents the rules and the exemption annotation.

PY ?= python

lint:
	$(PY) -m tools.fabriclint

san:
	$(PY) -m tools.fabriclint.san

native:
	$(MAKE) -C src

test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow' -p no:cacheprovider

.PHONY: lint san native test
