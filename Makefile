# Repo-level developer entry points.
#
#   make lint  — fabriclint (FFI signature cross-check, hot-path purity,
#                flag/bvar registry lint, callback keepalive, tb_* return
#                audit) AND fabricverify (lock-order graph, lifecycle
#                balance, protocol model checking) AND fabricscan
#                (C++-plane wire-bounds taint dataflow, reactor-ownership
#                checking, cross-plane parity lint); all three run, exit
#                codes merged (tools/fabriclint + tools/fabricverify +
#                tools/fabricscan; the same checks run inside tier-1 via
#                tests/test_static_analysis.py)
#   make verify-models — the explicit-state model checker alone, with
#                per-model state counts on stdout
#   make san   — sanitizer harness: ASAN+UBSAN over the native test
#                subset, TSAN over the telemetry-ring stress and the
#                scheduler (worker_pool + timer_thread) contention stress
#                (probe-gated: skips cleanly where the toolchain lacks
#                support)
#   make native — the plain native runtime build (src/build/libtbutil.so)
#   make test  — the tier-1 test suite
#
# docs/ANALYSIS.md documents the rules, the exemption annotation, and the
# generated lock hierarchy.

PY ?= python

lint:
	@rc=0; \
	$(PY) -m tools.fabriclint || rc=1; \
	$(PY) -m tools.fabricverify || rc=1; \
	$(PY) -m tools.fabricscan || rc=1; \
	exit $$rc

verify-models:
	$(PY) -m tools.fabricverify.modelcheck

san:
	$(PY) -m tools.fabriclint.san

native:
	$(MAKE) -C src

test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow' -p no:cacheprovider

.PHONY: lint verify-models san native test
