"""Parity bench — runs on one real TPU chip; prints ONE JSON line.

Measures the tensor-echo RPC step (the echo_c++ / rdma_performance analog,
BASELINE.md config #1/#5) with the payload resident in HBM: per-request
latency for small frames and sustained GB/s for large frames through the
full device-side parse→verify→dispatch→respond path.

Baseline anchor (BASELINE.md): reference same-machine large-payload
throughput ~2.3 GB/s (docs/cn/benchmark.md:106). ``vs_baseline`` is our
GB/s / 2.3.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp


def _bench_one(step, request, iters: int, warmup: int = 5):
    for _ in range(warmup):
        out = step(request)
    out.block_until_ready()
    t0 = time.perf_counter()
    for _ in range(iters):
        out = step(request)
    out.block_until_ready()
    dt = time.perf_counter() - t0
    return dt / iters


def main() -> None:
    from incubator_brpc_tpu.models.tensor_echo import make_echo_step

    results = {}

    # Large-frame throughput (streaming/rdma_performance analog): 8 MiB payload
    words_large = 2 * 1024 * 1024  # 8 MiB of uint32
    step, request = make_echo_step(payload_words=words_large)
    per_call = _bench_one(step, request, iters=30)
    bytes_moved = words_large * 4  # one payload per pass (convention: count once)
    gbps = bytes_moved / per_call / 1e9
    results["large_frame_gbps"] = gbps

    # Small-frame latency (echo qps analog): 256-word payload
    step_s, request_s = make_echo_step(payload_words=256)
    per_call_s = _bench_one(step_s, request_s, iters=200)
    results["small_frame_us"] = per_call_s * 1e6
    results["small_frame_qps"] = 1.0 / per_call_s

    baseline_gbps = 2.3  # reference same-machine large-payload max (BASELINE.md)
    print(
        json.dumps(
            {
                "metric": "tensor_echo_throughput",
                "value": round(gbps, 3),
                "unit": "GB/s",
                "vs_baseline": round(gbps / baseline_gbps, 3),
                "detail": {
                    "payload_mib": words_large * 4 / 2**20,
                    "small_frame_us": round(results["small_frame_us"], 2),
                    "small_frame_qps": round(results["small_frame_qps"]),
                    "device": str(jax.devices()[0]),
                    "baseline": "brpc same-machine >=32KB multi-conn ~2.3 GB/s (docs/cn/benchmark.md:106); NOTE: on-device HBM echo vs the reference's network loopback — not apples-to-apples",
                },
            }
        )
    )


if __name__ == "__main__":
    main()
