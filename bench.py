"""Parity bench — runs on one real TPU chip.

Output contract (VERDICT r5 weak #1): the baseline commentary prints
FIRST as prose on stderr, then stdout carries exactly TWO JSON lines —
a full ``detail`` blob, and LAST a compact headline line — so a consumer
reading only the tail of the output always gets the headline metrics
(the driver's 2000-char tail used to truncate them away).

Three surfaces, matching BASELINE.md / VERDICT round-1 guidance:

1. Device tensor-echo (echo_c++ / rdma_performance analog): the fused
   parse→verify→dispatch→respond step over an HBM-resident frame. Large
   frames give GB/s, small frames give per-call latency.
2. End-to-end RPC echo over the host loopback transport: real
   Channel→Socket→Server→response path (the reference's same-machine echo,
   docs/cn/benchmark.md:57 — 200-300 ns/req, 3-5 M qps/thread on 2015
   hardware), plus streaming GB/s through the credit-window stream API
   (reference same-machine large-payload ~2.3 GB/s, benchmark.md:106).
3. FabricNet train step on the real chip: ms/step and achieved MFU against
   peak bf16 (v5e ≈ 197 TFLOP/s/chip), using XLA cost analysis for the
   exact FLOP count.

The headline metric stays the device-path throughput (it is the
transport=tpu story); the honest host-plane numbers ride in ``detail``.
"""

from __future__ import annotations

import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

V5E_PEAK_BF16 = 197e12  # FLOP/s per chip

# Every repeated row records its raw samples here; the output carries
# {row: {"median": m, "min": lo, "max": hi, "n": k}} so a single noisy
# pass on this shared 1-core host can never masquerade as a regression
# (or an improvement) again.
SAMPLES: dict = {}


def _record(name: str, samples) -> None:
    xs = [float(x) for x in samples]
    SAMPLES[name] = {
        "median": round(float(np.median(xs)), 3),
        "min": round(min(xs), 3),
        "max": round(max(xs), 3),
        "n": len(xs),
    }


def _sync(out) -> None:
    """Synchronize by pulling ONE element to the host. block_until_ready is
    not a reliable barrier over a tunneled TPU backend (it can return before
    the device finishes); a host read of any element is, because the value
    cannot materialize before the computation does."""
    leaf = jax.tree_util.tree_leaves(out)[-1]
    idx = (0,) * leaf.ndim
    np.asarray(jax.device_get(leaf[idx]))


def _bench_one(step, request, iters: int, warmup: int = 5):
    for _ in range(warmup):
        out = step(request)
    _sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = step(request)
    _sync(out)
    dt = time.perf_counter() - t0
    return dt / iters


def bench_device_echo(results: dict) -> None:
    from incubator_brpc_tpu.models.tensor_echo import make_echo_step

    # 256 MiB per frame: large enough that the per-dispatch host→device
    # submission latency (the fixed cost any one-call-at-a-time client pays)
    # amortizes against HBM-bound compute — the multi-connection sustained
    # throughput shape of the reference's >=32KB test
    words_large = 64 * 1024 * 1024
    step, request = make_echo_step(payload_words=words_large)
    per_call = _bench_one(step, request, iters=10)
    results["large_frame_gbps"] = words_large * 4 / per_call / 1e9

    step_s, request_s = make_echo_step(payload_words=256)
    calls = [_bench_one(step_s, request_s, iters=200) for _ in range(5)]
    _record("small_frame_us", [c * 1e6 for c in calls])
    per_call_s = min(calls)  # latency: noise only ever adds
    results["small_frame_us"] = per_call_s * 1e6
    results["small_frame_qps"] = 1.0 / per_call_s


def bench_rpc_echo(results: dict) -> None:
    """Two-party echo over the loopback transport: Channel → Socket write →
    dispatcher → Server handler → response → correlation-id wake."""
    from incubator_brpc_tpu.rpc import (
        Channel,
        Server,
        ServerOptions,
        StreamHandler,
        StreamOptions,
        stream_accept,
        stream_create,
    )

    done = threading.Event()
    total = 64 * 1024 * 1024
    seen = [0]

    class Sink(StreamHandler):
        def on_received_messages(self, s, msgs):
            seen[0] += sum(len(m) for m in msgs)
            if seen[0] >= total:
                done.set()

    def open_stream(cntl, req):
        # raw_messages: handlers get zero-copy IOBufs — the reference
        # contract (stream.h hands butil::IOBuf*s), and what its ~0.8 GB/s
        # single-conn stream row measures
        stream_accept(
            cntl,
            StreamOptions(
                handler=Sink(), max_buf_size=32 << 20, raw_messages=True
            ),
        )
        return b""

    # echo/stream handlers never block: run them inline on the reactors
    # (ServerOptions.usercode_inline — the tuning a non-blocking service
    # uses in production, analogous to the reference's usercode knobs)
    server = Server(ServerOptions(usercode_inline=True))
    server.add_service("bench", {"echo": lambda cntl, req: req})
    server.add_service("bench_stream", {"open": open_stream})
    started = server.start(0)
    assert started
    ch = Channel()
    inited = ch.init(f"127.0.0.1:{server.port}")
    assert inited

    payload = b"x" * 64
    for _ in range(50):  # warmup
        c = ch.call_method("bench", "echo", payload)
        assert c.ok(), c.error_text

    n = 2000
    lat = []
    for _ in range(5):
        nerr = 0
        t0 = time.perf_counter()
        for _ in range(n):
            if ch.call_method("bench", "echo", payload).failed():
                nerr += 1
        dt = time.perf_counter() - t0
        assert nerr == 0, f"{nerr}/{n} echo calls failed during latency run"
        lat.append(dt / n * 1e6)
    _record("rpc_echo_py_us", lat)
    results["rpc_echo_py_us"] = min(lat)

    # concurrent qps: 8 caller threads, sync calls
    nthreads, per_thread = 8, 1000
    errs = []

    def worker():
        for _ in range(per_thread):
            c = ch.call_method("bench", "echo", payload)
            if c.failed():
                errs.append(c.error_code)

    threads = [threading.Thread(target=worker) for _ in range(nthreads)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    results["rpc_echo_py_qps"] = (nthreads * per_thread - len(errs)) / dt

    # streaming GB/s through the credit window — three passes, best kept
    # (this host is shared; a single pass can land in someone else's burst)
    chunk = b"z" * (1024 * 1024)
    rates = []
    for _ in range(5):
        seen[0] = 0
        done.clear()
        s = stream_create(StreamOptions(max_buf_size=32 << 20))
        c = ch.call_method("bench_stream", "open", b"", request_stream=s)
        assert c.ok(), c.error_text
        connected = s.wait_connected(5)
        assert connected
        t0 = time.perf_counter()
        sent = 0
        while sent < total:
            rc = s.write(chunk, timeout=30)
            assert rc == 0, f"stream write rc={rc}"
            sent += len(chunk)
        drained = done.wait(timeout=60)
        assert drained
        dt = time.perf_counter() - t0
        rates.append(total / dt / 1e9)
        s.close()
    _record("stream_gbps", rates)
    results["stream_gbps"] = max(rates)
    server.stop()


def bench_native_plane(results: dict) -> None:
    """The native data plane (src/tbnet): echo through the C++ reactor +
    dispatcher with native client. Three numbers:
    - rpc_echo_us: sync Channel.call_method latency over the native path
      (the framework's sanctioned fast path: ChannelOptions(native_plane));
    - rpc_echo_qps: 8 sync caller threads (GIL-bound Python L5 on top of
      the native plane — the honest cost of the Python user API);
    - native_pump_ns/qps: pipelined per-request processing cost measured
      entirely in C++ (the comparable for the reference's 200-300 ns/req
      single-thread echo number, docs/cn/benchmark.md:57);
    - native_echo_32k_gbps: 32 KiB echo throughput, single connection
      (the reference's large-request table, benchmark.md:106)."""
    from incubator_brpc_tpu.rpc import (
        Channel,
        ChannelOptions,
        Server,
        ServerOptions,
        native_echo,
    )
    from incubator_brpc_tpu.transport import native_plane as np_mod

    if not np_mod.NET_AVAILABLE:
        return
    server = Server(
        ServerOptions(native_plane=True, usercode_inline=True, native_loops=2)
    )
    server.add_service("bench", {"echo": native_echo})
    assert server.start(0)
    assert server._native_plane is not None
    ch = Channel()
    assert ch.init(
        f"127.0.0.1:{server.port}", options=ChannelOptions(native_plane=True)
    )
    payload = b"x" * 64
    for _ in range(100):
        c = ch.call_method("bench", "echo", payload)
        assert c.ok(), c.error_text
    n = 3000
    lat = []
    for _ in range(5):
        t0 = time.perf_counter()
        for _ in range(n):
            if ch.call_method("bench", "echo", payload).failed():
                raise AssertionError("native echo failed mid-run")
        lat.append((time.perf_counter() - t0) / n * 1e6)
    _record("rpc_echo_us", lat)
    results["rpc_echo_us"] = min(lat)

    nthreads, per = 8, 2000
    errs = []

    def worker():
        for _ in range(per):
            if ch.call_method("bench", "echo", payload).failed():
                errs.append(1)

    threads = [threading.Thread(target=worker) for _ in range(nthreads)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    assert not errs, f"{len(errs)} native echo calls failed"
    results["rpc_echo_qps"] = nthreads * per / dt

    nch = np_mod.NativeClientChannel("127.0.0.1", server.port)
    try:
        nch.pump("bench", "echo", payload, 2000, inflight=64)  # warm
        pump = [
            nch.pump("bench", "echo", payload, 100000, inflight=128)
            for _ in range(5)
        ]
        _record("native_pump_ns", pump)
        best = min(pump)
        results["native_pump_ns"] = best
        results["native_pump_qps"] = 1e9 / best
        big = b"x" * 32768
        ns32 = [nch.pump("bench", "echo", big, 10000, inflight=32) for _ in range(3)]
        # bidirectional: the payload crosses the loopback twice per request
        _record("native_echo_32k_gbps", [2 * len(big) / v for v in ns32])
        results["native_echo_32k_gbps"] = 2 * len(big) / min(ns32)
    finally:
        nch.close()

    # baidu_std (PRPC) on the SAME native plane: the canonical wire
    # protocol cut, dispatched and packed in C++ (no interpreter on the
    # hot path). rpc_echo_prpc_us crosses the Python L5 API over PRPC;
    # prpc_pump_ns is the interpreter-free pipelined comparable for the
    # reference's 200-300 ns/req single-thread baidu_std echo
    # (docs/cn/benchmark.md:57) — the row that used to pay the 6-7x
    # Python tax through the Socket reactor.
    chp = Channel()
    assert chp.init(
        f"127.0.0.1:{server.port}",
        options=ChannelOptions(native_plane=True, protocol="baidu_std"),
    )
    for _ in range(100):
        c = chp.call_method("bench", "echo", payload)
        assert c.ok(), c.error_text
    lat = []
    for _ in range(5):
        t0 = time.perf_counter()
        for _ in range(n):
            if chp.call_method("bench", "echo", payload).failed():
                raise AssertionError("prpc echo failed mid-run")
        lat.append((time.perf_counter() - t0) / n * 1e6)
    _record("rpc_echo_prpc_us", lat)
    results["rpc_echo_prpc_us"] = min(lat)

    nchp = np_mod.NativeClientChannel(
        "127.0.0.1", server.port, protocol="baidu_std"
    )
    try:
        nchp.pump("bench", "echo", payload, 2000, inflight=64)  # warm
        pump = [
            nchp.pump("bench", "echo", payload, 100000, inflight=128)
            for _ in range(5)
        ]
        _record("prpc_pump_ns", pump)
        best = min(pump)
        results["prpc_pump_ns"] = best
        results["prpc_pump_qps"] = 1e9 / best
    finally:
        nchp.close()

    # traced flood on the same plane (ISSUE 15): every frame carries the
    # Dapper trace fields + the head-based sampled bit in its
    # RpcRequestMeta (the pump's counter-scheduled traced template), and
    # the cutter decodes them natively — BEFORE this PR the same wire
    # shape fell off to the ~35 us Python route (the ~60x observability
    # tax ROADMAP item 1 names).  Acceptance: within ~1.15x of the bare
    # pump, cb_frames == 0 (checked in tests/test_tracing.py).
    from incubator_brpc_tpu.utils.flags import flag_registry as _freg
    from incubator_brpc_tpu.utils.flags import set_flag_unchecked as _setf

    old_rpcz = _freg.get("enable_rpcz")
    _setf("enable_rpcz", True)  # production-shaped: spans actually collect
    ncht = np_mod.NativeClientChannel(
        "127.0.0.1", server.port, protocol="baidu_std"
    )
    try:
        ncht.pump("bench", "echo", payload, 2000, inflight=64)  # warm
        # INTERLEAVED bare/traced rounds: the ratio is the claim, and on
        # a shared host back-to-back blocks would attribute scheduler
        # noise to the trace seam — each round flips the template
        bare_i, traced = [], []
        for _ in range(5):
            ncht.set_trace(trace_id=0, every=0)
            bare_i.append(
                ncht.pump("bench", "echo", payload, 50000, inflight=128)
            )
            ncht.set_trace(
                trace_id=0xBE7C4, span_id=1, parent_span_id=0x1,
                sampled=1, every=1,
            )
            traced.append(
                ncht.pump("bench", "echo", payload, 50000, inflight=128)
            )
        _record("prpc_traced_pump_ns", traced)
        results["prpc_traced_pump_ns"] = min(traced)
        results["prpc_traced_vs_bare"] = min(traced) / min(bare_i)
        cb = server._native_plane.stats()["cb_frames"]
        results["prpc_traced_cb_frames"] = cb
        assert cb == 0, "traced pump frames fell off the fast path"
    finally:
        ncht.close()
        _setf("enable_rpcz", old_rpcz)
    server.stop()

    # the telemetry tax: prpc_pump_ns above runs with the completion-record
    # ring ON (the default — per-method latency, rpcz sampling, limiter
    # feedback for natively-dispatched requests); the same pump against a
    # ring-less server isolates the hot path's added cost (one CAS + two
    # clock reads + a few stores per request; acceptance: < 5%)
    from incubator_brpc_tpu.utils.flags import flag_registry, set_flag_unchecked

    old_tel = flag_registry.get("native_telemetry")
    set_flag_unchecked("native_telemetry", False)
    try:
        server2 = Server(
            ServerOptions(
                native_plane=True, usercode_inline=True, native_loops=2
            )
        )
        server2.add_service("bench", {"echo": native_echo})
        assert server2.start(0)
        assert server2._native_plane is not None
        nch2 = np_mod.NativeClientChannel(
            "127.0.0.1", server2.port, protocol="baidu_std"
        )
        try:
            nch2.pump("bench", "echo", payload, 2000, inflight=64)  # warm
            pump0 = [
                nch2.pump("bench", "echo", payload, 100000, inflight=128)
                for _ in range(5)
            ]
            _record("prpc_pump_notelem_ns", pump0)
            results["prpc_pump_notelem_ns"] = min(pump0)
        finally:
            nch2.close()
        server2.stop()
    finally:
        set_flag_unchecked("native_telemetry", old_tel)

    # pooled multi-connection large payloads (the reference's headline
    # ~2.3 GB/s same-machine >=32KB multi-connection row,
    # docs/cn/benchmark.md:106): 4 connections over a 2-loop server, 32 KiB
    # echoes pumped concurrently; bytes cross the loopback twice per call
    srv = Server(
        ServerOptions(native_plane=True, usercode_inline=True, native_loops=2)
    )
    srv.add_service("bench", {"echo": native_echo})
    assert srv.start(0)
    nconns, per, big = 4, 4000, b"p" * 32768
    chans = [
        np_mod.NativeClientChannel("127.0.0.1", srv.port) for _ in range(nconns)
    ]
    try:
        for nc in chans:
            nc.pump("bench", "echo", big, 200, inflight=16)  # warm
        pooled = []
        for _ in range(3):
            errs = []

            def big_puller(nc):
                try:
                    nc.pump("bench", "echo", big, per, inflight=32)
                except Exception as e:  # noqa: BLE001
                    errs.append(e)

            threads = [
                threading.Thread(target=big_puller, args=(nc,)) for nc in chans
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            dt = time.perf_counter() - t0
            assert not errs, errs[:1]
            pooled.append(2 * len(big) * nconns * per / dt / 1e9)
        _record("pooled_32k_gbps", pooled)
        results["pooled_32k_gbps"] = max(pooled)
    finally:
        for nc in chans:
            nc.close()
        srv.stop()

    bench_native_scaling(results)


def bench_prpc_production(results: dict) -> None:
    """Production-shaped PRPC traffic on the native plane: compressed
    and/or authenticated 4 KiB echo floods, all-C++ end to end (codec +
    auth seam live in src/tbnet since this row exists). Rows:
    - prpc_plain_4k_pump_ns: the bare same-size comparable;
    - prpc_compressed_pump_ns: snappy, compressible 4 KiB (the ~2x-of-
      bare acceptance row; used to pay the ~60x Python-route tax);
    - prpc_compressed_incompressible_pump_ns: snappy over random bytes
      (worst-case parse, no wire savings);
    - prpc_auth_pump_ns: authenticated (token-table) flood, uncompressed;
    - rpc_echo_prpc_snappy_us: the Python L5 Channel crossing with
      compress+auth — and rpc_echo_prpc_snappy_python_us, the SAME wire
      shape via the pure-Python plane (the before-number that makes the
      60x→2x claim a measured delta)."""
    from incubator_brpc_tpu.protocol import compress as compress_mod
    from incubator_brpc_tpu.rpc import (
        Channel,
        ChannelOptions,
        Controller,
        Server,
        ServerOptions,
        TokenAuthenticator,
        native_echo,
    )
    from incubator_brpc_tpu.transport import native_plane as np_mod

    if not np_mod.NET_AVAILABLE:
        return
    token = "bench-token"
    payload = (b"The quick brown fox jumps over the lazy dog. " * 92)[:4096]
    incompressible = os.urandom(4096)

    def make_server(**kw):
        srv = Server(
            ServerOptions(usercode_inline=True, native_loops=1, **kw)
        )
        srv.add_service("bench", {"echo": native_echo})
        assert srv.start(0)
        return srv

    def pump_row(name, port, data, compress="", auth=""):
        nch = np_mod.NativeClientChannel(
            "127.0.0.1", port, protocol="baidu_std"
        )
        try:
            if auth:
                nch.set_auth(auth)
            wire = data
            if compress:
                nch.set_request_compress(compress)
                wire = compress_mod.compress(compress, data)
            nch.pump("bench", "echo", wire, 2000, inflight=64)  # warm
            samples = [
                nch.pump("bench", "echo", wire, 20000, inflight=128)
                for _ in range(5)
            ]
            _record(name, samples)
            results[name] = min(samples)
        finally:
            nch.close()

    def echo_row(name, port, opts, n):
        """L5 compressed-echo latency through whatever plane ``opts``
        selects — one measurement discipline for the native row and the
        pure-Python before-number, so they stay comparable."""
        ch = Channel()
        assert ch.init(f"127.0.0.1:{port}", options=opts)
        for _ in range(50):
            cntl = Controller()
            cntl.compress_type = "snappy"
            c = ch.call_method("bench", "echo", payload, cntl=cntl)
            assert c.ok(), c.error_text
        lat = []
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(n):
                cntl = Controller()
                cntl.compress_type = "snappy"
                if ch.call_method(
                    "bench", "echo", payload, cntl=cntl
                ).failed():
                    raise AssertionError(f"{name} echo failed mid-run")
            lat.append((time.perf_counter() - t0) / n * 1e6)
        _record(name, lat)
        results[name] = min(lat)

    # the BARE comparable runs on a no-auth server: the plain row must
    # measure neither codec nor credential work
    bare = make_server(native_plane=True)
    try:
        pump_row("prpc_plain_4k_pump_ns", bare.port, payload)
        assert bare._native_plane.stats()["cb_frames"] == 0
    finally:
        bare.stop()

    server = make_server(
        native_plane=True, auth=TokenAuthenticator([token])
    )
    try:
        pump_row(
            "prpc_compressed_pump_ns", server.port, payload,
            compress="snappy", auth=token,
        )
        pump_row(
            "prpc_compressed_incompressible_pump_ns", server.port,
            incompressible, compress="snappy", auth=token,
        )
        pump_row("prpc_auth_pump_ns", server.port, payload, auth=token)
        results["prpc_compressed_vs_plain_ratio"] = (
            results["prpc_compressed_pump_ns"]
            / results["prpc_plain_4k_pump_ns"]
        )
        # the whole flood stayed off the interpreter — the claim behind
        # every row above
        assert server._native_plane.stats()["cb_frames"] == 0
        echo_row(
            "rpc_echo_prpc_snappy_us",
            server.port,
            ChannelOptions(
                native_plane=True,
                protocol="baidu_std",
                auth=TokenAuthenticator([token]),
            ),
            n=500,
        )
    finally:
        server.stop()

    # the before-number: the SAME compressed+authenticated wire shape
    # through the pure-Python plane end to end (Python acceptor, Socket
    # reactor, Python codecs) — what this traffic paid before the native
    # codec/auth seam existed
    pyserver = make_server(auth=TokenAuthenticator([token]))
    try:
        echo_row(
            "rpc_echo_prpc_snappy_python_us",
            pyserver.port,
            ChannelOptions(
                protocol="baidu_std", auth=TokenAuthenticator([token])
            ),
            n=300,
        )
    finally:
        pyserver.stop()


def bench_native_scaling(results: dict) -> None:
    """Reactors × connections scaling matrix (the reference's per-thread
    scaling table, docs/cn/benchmark.md:112-122): R per-core reactors
    serving C connections pumped concurrently, each from its own thread —
    tb_channel_pump runs in C++ with the GIL released, so the client
    threads genuinely overlap, and the server spreads its cut/dispatch/
    pack work across the reactors. The headline ratio is
    scaling_efficiency = best 4-reactor qps / best 1-reactor qps: the
    one-core ceiling (BENCH_r05's 544 ns / ~1.9 M qps, one shared core)
    is broken exactly when this exceeds 1."""
    from incubator_brpc_tpu.rpc import Server, ServerOptions, native_echo
    from incubator_brpc_tpu.transport import native_plane as np_mod

    if not np_mod.NET_AVAILABLE:
        return
    payload = b"x" * 64
    per_conn = 60000
    for reactors in (1, 2, 4):
        srv = Server(
            ServerOptions(native_plane=True, usercode_inline=True,
                          num_reactors=reactors)
        )
        srv.add_service("bench", {"echo": native_echo})
        assert srv.start(0)
        try:
            for conns in (1, 2, 4):
                chans = [
                    np_mod.NativeClientChannel("127.0.0.1", srv.port)
                    for _ in range(conns)
                ]
                try:
                    for nc in chans:  # warm every connection/reactor pairing
                        nc.pump("bench", "echo", payload, 2000, inflight=64)
                    best = 0.0
                    for _rep in range(3):  # best-of-3: co-tenant noise on
                        errs = []          # shared cores swamps one rep

                        def puller(nc):
                            try:
                                nc.pump(
                                    "bench", "echo", payload, per_conn,
                                    inflight=128,
                                )
                            except Exception as e:  # noqa: BLE001
                                errs.append(e)

                        threads = [
                            threading.Thread(target=puller, args=(nc,))
                            for nc in chans
                        ]
                        t0 = time.perf_counter()
                        for t in threads:
                            t.start()
                        for t in threads:
                            t.join()
                        dt = time.perf_counter() - t0
                        assert not errs, errs[:1]
                        best = max(best, conns * per_conn / dt)
                    results[f"native_pump_qps_r{reactors}c{conns}"] = best
                finally:
                    for nc in chans:
                        nc.close()
        finally:
            srv.stop()
    best1 = max(
        results.get(f"native_pump_qps_r1c{c}", 0) for c in (1, 2, 4)
    )
    best4 = max(
        results.get(f"native_pump_qps_r4c{c}", 0) for c in (1, 2, 4)
    )
    if best1 > 0:
        results["native_pump_scaling_efficiency"] = best4 / best1


def bench_device_rpc(results: dict) -> None:
    """The transport=tpu path end to end: RPC over loopback whose handler
    runs the fused device step (DeviceEndpoint.server_handler)."""
    from incubator_brpc_tpu.rpc import Channel, Controller, Server
    from incubator_brpc_tpu.transport.device import DeviceEndpoint
    from incubator_brpc_tpu.utils.flags import set_flag_unchecked

    # enough CQ watchers that completions (each a tunneled device fetch,
    # ~100-250 ms here) overlap up to the window, not up to 2 — the
    # reference sizes rdma_cq_num for its poller pool the same way
    set_flag_unchecked("device_cq_threads", 8)
    ep = DeviceEndpoint(window_size=16)
    server = Server()
    server.add_service("tensor", {"echo": ep.server_handler()})
    started = server.start(0)
    assert started
    ch = Channel()
    inited = ch.init(f"127.0.0.1:{server.port}")
    assert inited
    payload = b"d" * 256
    # warm (first call compiles the device program; the handler's own 10s
    # device budget can expire mid-compile on a loaded host — retry)
    for _ in range(6):
        c = ch.call_method(
            "tensor", "echo", payload, cntl=Controller(timeout_ms=120000)
        )
        if c.ok():
            break
        time.sleep(2)
    assert c.ok(), c.error_text

    # sequential latency
    n = 20
    t0 = time.perf_counter()
    for _ in range(n):
        c = ch.call_method(
            "tensor", "echo", payload, cntl=Controller(timeout_ms=30000)
        )
        assert c.ok(), c.error_text
    results["device_rpc_us"] = (time.perf_counter() - t0) / n * 1e6

    # pipelined throughput: enough callers to keep the credit window full
    # so dispatches and readbacks overlap (the per-WR pipelining the
    # window exists for). Concurrent calls micro-batch into vmapped
    # dispatches — warm every (batch, bucket) geometry DETERMINISTICALLY
    # first (a concurrency burst warms only whatever batch sizes arrival
    # timing happens to form) so the timed run measures dispatch, not
    # XLA compilation.
    ep.warm(len(payload))
    nthreads, per = 16, 8
    errs = []

    def worker():
        for _ in range(per):
            c = ch.call_method(
                "tensor", "echo", payload, cntl=Controller(timeout_ms=60000)
            )
            if c.failed():
                errs.append(c.error_code)

    threads = [threading.Thread(target=worker) for _ in range(nthreads)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    assert not errs, f"{len(errs)} pipelined device RPCs failed"
    results["device_rpc_qps"] = nthreads * per / dt
    server.stop()


def bench_device_link(results: dict) -> None:
    """transport=tpu end to end: the two-party device link (handshake over
    the host socket, frames over the link steps). On this bench host both
    parties share the one real chip, so the link runs its shared-device
    fast path: the exchange is a host swap — all the link machinery (slot
    packing, seq/ack headers, credit window, in-order delivery, messenger
    re-cut) runs, without paying two tunnel crossings per step for a swap
    that moves no information. Two numbers:
    - device_link_echo_us: full RPC echo over the link (handshake amortized);
    - link_stream_gbps: window-saturated byte-stream throughput through
      the link itself (the rdma_performance data-rate analog,
      /root/reference/example/rdma_performance/client.cpp:32-40)."""
    from incubator_brpc_tpu.rpc import Channel, ChannelOptions, Server, ServerOptions

    server = Server(ServerOptions(usercode_inline=True))
    server.add_service("bench", {"echo": lambda cntl, req: req})
    assert server.start(0)
    ch = Channel()
    assert ch.init(
        f"127.0.0.1:{server.port}",
        options=ChannelOptions(transport="tpu", timeout_ms=120000),
    )
    payload = b"d" * 1024
    c = ch.call_method("bench", "echo", payload)  # warm: first link step
    assert c.ok(), c.error_text
    n = 200
    t0 = time.perf_counter()
    for _ in range(n):
        c = ch.call_method("bench", "echo", payload)
        assert c.ok(), c.error_text
    results["device_link_echo_us"] = (time.perf_counter() - t0) / n * 1e6
    server.stop()

    # link-level throughput: big slots, window >= 8, pipelined sends with
    # delivery overlapping the next fill (best of 3 on this shared host)
    import jax as _jax

    from incubator_brpc_tpu.transport.device_link import DeviceLink, DeviceSocket

    class _Sink:
        def __init__(self):
            self.nbytes = 0

        def process(self, sock):
            n = len(sock._read_buf)
            sock._read_buf.popn(n)
            self.nbytes += n

    dev = _jax.devices()[0]
    chunk = b"s" * (1 << 20)
    total = 256 << 20

    def _one_stream(ack_mode: str) -> float:
        link = DeviceLink(
            [dev, dev], slot_words=256 * 1024, window=8, ack_mode=ack_mode
        )
        DeviceSocket(link, side=0, messenger=_Sink())
        sink = _Sink()
        DeviceSocket(link, side=1, messenger=sink)
        t0 = time.perf_counter()
        for _ in range(total // len(chunk)):
            rc = link.send(0, chunk, timeout=60)
            assert rc == 0, f"link send rc={rc}"
        deadline = time.monotonic() + 120
        while sink.nbytes < total and time.monotonic() < deadline:
            time.sleep(0.001)
        assert sink.nbytes >= total, "link stream did not drain"
        rate = total / (time.perf_counter() - t0) / 1e9
        link.fail("bench done")
        return rate

    # 'wire' re-runs the stream with the multi-controller credit flow
    # (window gated on the acks carried in received slot headers). The
    # two modes are INTERLEAVED in pairs with ALTERNATING order
    # (local,wire / wire,local / ...) so both see the same co-tenant
    # drift on this shared core AND neither systematically pays the
    # runs-second position; both modes warm before anything is recorded
    # and gc runs between streams (allocator churn from the retired
    # links otherwise lands on whoever runs next). The per-pair ratio
    # median is the drift-normalized comparison the old sequential
    # blocks never were — measured this way the r05 "6.6% wire gap"
    # disappears into noise (ratio median ~1.0 on this container).
    import gc as _gc

    _one_stream("local")
    _one_stream("wire")  # warm both modes off the record
    local_rates, wire_rates, ratios = [], [], []
    for rep in range(12):
        order = ("local", "wire") if rep % 2 == 0 else ("wire", "local")
        pair = {}
        for mode in order:
            _gc.collect()
            pair[mode] = _one_stream(mode)
        local_rates.append(pair["local"])
        wire_rates.append(pair["wire"])
        ratios.append(pair["wire"] / pair["local"])
    _record("link_stream_gbps", local_rates)
    _record("link_stream_wire_gbps", wire_rates)
    _record("link_stream_wire_vs_local", ratios)
    results["link_stream_gbps"] = max(local_rates)
    results["link_stream_wire_gbps"] = max(wire_rates)
    # the pairwise median, NOT max(wire)/max(local): each ratio compares
    # two runs that shared one drift window
    results["link_stream_wire_vs_local_pct"] = (
        float(np.median(ratios)) * 100.0
    )


def bench_fabricnet(results: dict) -> None:
    """Flagship train loop on the real chip at a bench-scale config.

    The measured unit is an on-device training LOOP: ``lax.scan`` chains
    ``nsteps`` full train steps (forward + backward + SGD) per dispatch,
    each step's params feeding the next — genuinely sequential work a
    smart runtime cannot overlap or elide, with the per-dispatch host→TPU
    submission gap (10+ ms over this tunnel) amortized the way any real
    training loop amortizes it. FLOPs come from XLA's own cost analysis of
    ONE un-scanned step (scan bodies are undercounted by cost_analysis;
    microbatches=1 also keeps the pipeline's inner scan at one tick so the
    count is exact)."""
    from incubator_brpc_tpu.models import fabricnet
    from incubator_brpc_tpu.parallel.mesh import make_fabric_mesh

    mesh = make_fabric_mesh(n_devices=1, devices=jax.devices()[:1])
    cfg = fabricnet.FabricNetConfig(
        d_model=2048,
        d_ff=8192,
        d_expert=2048,
        experts_per_rank=2,
        layers_per_stage=4,
        batch=4,
        seq=1024,
        microbatches=1,
        dtype=jnp.bfloat16,
    )
    fabricnet.validate_config(cfg, mesh)
    params = fabricnet.init_params(cfg, mesh)
    x, y = fabricnet.make_batch(cfg, mesh)
    step = fabricnet.make_train_step(cfg, mesh)

    flops = None
    try:
        ca = step.lower(params, x, y).compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        flops = float(ca.get("flops", 0.0)) or None
    except Exception:
        pass

    nsteps = 10

    def loop(params, x, y):
        return jax.lax.scan(lambda p, _: step(p, x, y), params, None, length=nsteps)

    compiled = jax.jit(loop, donate_argnums=(0,)).lower(params, x, y).compile()
    out = compiled(params, x, y)  # warm; donates params
    del params
    _sync(out[1])  # [1] = the per-step losses
    iters = 5
    t0 = time.perf_counter()
    for _ in range(iters):
        out = compiled(out[0], x, y)
    _sync(out[1])
    dt = (time.perf_counter() - t0) / iters / nsteps
    results["fabricnet_step_ms"] = dt * 1e3
    if flops:
        results["fabricnet_tflops"] = flops / dt / 1e12
        results["fabricnet_mfu_pct"] = flops / dt / V5E_PEAK_BF16 * 100.0


def bench_fabricnet_overlap(results: dict) -> None:
    """Same-process serialized-vs-overlapped A/B of the T3 microbatch
    schedule (docs/DEVICE_PLANE.md "overlap scheduler"): the bench-scale
    fabricnet config at microbatches=2 trained under both schedules —
    identical ops, the serialized variant's optimization_barrier pinning
    each slice's gradient collectives before the next slice's forward —
    interleaved best-of-3 per mode so host drift hits both equally.  The
    per-step delta is the idle gap the barrier costs; the schedules must
    stay BIT-identical (asserted here, not just in tests).  The config
    stays at bench scale on every backend — the barrier's cost scales
    with the model, and a scaled-down CPU config measured the gap inside
    run-to-run noise — but on a CPU backend the scan length halves
    (emulated bf16 runs this config at ~20 s/step; the per-step gap is
    per-step, the shorter chain only widens the noise floor the
    interleaved best-of-3 min already guards)."""
    import gc

    from incubator_brpc_tpu.models import fabricnet
    from incubator_brpc_tpu.parallel.mesh import make_fabric_mesh

    mesh = make_fabric_mesh(n_devices=1, devices=jax.devices()[:1])
    on_cpu = jax.devices()[0].platform == "cpu"
    nsteps = 5 if on_cpu else 10
    cfg = fabricnet.FabricNetConfig(
        d_model=2048,
        d_ff=8192,
        d_expert=2048,
        experts_per_rank=2,
        layers_per_stage=4,
        batch=4,
        seq=1024,
        microbatches=2,  # the schedule slices — the A/B's subject
        dtype=jnp.bfloat16,
    )
    results["fabricnet_overlap_config"] = (
        f"d{cfg.d_model}/ff{cfg.d_ff}/L{cfg.layers_per_stage}"
        f"/s{cfg.seq}/n{nsteps}"
    )
    fabricnet.validate_config(cfg, mesh)
    params = fabricnet.init_params(cfg, mesh)
    x, y = fabricnet.make_batch(cfg, mesh)

    steps = {
        "serialized": fabricnet.make_train_step(cfg, mesh, schedule="serialized"),
        "overlapped": fabricnet.make_train_step(cfg, mesh, schedule="overlapped"),
    }
    flops = None
    try:
        ca = (
            steps["overlapped"].lower(params, x, y).compile().cost_analysis()
        )
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        flops = float(ca.get("flops", 0.0)) or None
    except Exception:
        pass

    compiled = {}
    losses = {}
    for mode, step in steps.items():
        def loop(params, x, y, _step=step):
            return jax.lax.scan(
                lambda p, _: _step(p, x, y), params, None, length=nsteps
            )

        compiled[mode] = jax.jit(loop).lower(params, x, y).compile()
        out = compiled[mode](params, x, y)  # warm
        _sync(out[1])
        losses[mode] = np.asarray(out[1]).tobytes()
    # byte-identity gate on the warm runs: the CHAINED per-step losses
    # (each step's params feeding the next) must match bitwise across
    # schedules — the barrier is an identity, only emission order moves
    identical = losses["serialized"] == losses["overlapped"]
    results["fabricnet_sched_identical"] = identical
    assert identical, "overlapped schedule diverged from serialized"
    per_step_ms: dict = {"serialized": [], "overlapped": []}
    for rep in range(3):
        order = (
            ("serialized", "overlapped") if rep % 2 == 0
            else ("overlapped", "serialized")
        )
        for mode in order:
            gc.collect()
            t0 = time.perf_counter()
            out = compiled[mode](params, x, y)
            _sync(out[1])
            per_step_ms[mode].append(
                (time.perf_counter() - t0) / nsteps * 1e3
            )
    for mode, xs in per_step_ms.items():
        _record(f"fabricnet_sched_{mode}_step_ms", xs)
        results[f"fabricnet_sched_{mode}_step_ms"] = min(xs)
    ser, ovl = (
        results["fabricnet_sched_serialized_step_ms"],
        results["fabricnet_sched_overlapped_step_ms"],
    )
    # the serialization tax: per-step ms the barrier costs (communication
    # the overlapped schedule hides behind the next slice's compute)
    results["fabricnet_overlap_idle_gap_ms"] = ser - ovl
    if flops:
        results["fabricnet_overlap_mfu_pct"] = (
            flops / (ovl / 1e3) / V5E_PEAK_BF16 * 100.0
        )


def bench_mc_overlap(results: dict) -> None:
    """Chunked collective sessions A/B (parallel/mc_dispatch.py): a
    2-party in-process session on the virtual 8-device CPU mesh, chunked
    serialized (per-chunk ack barrier each step) vs double-buffered (two
    step slots in flight, acks trigger the next slice) — per-step ms per
    mode + the measured mc_dispatch_overlap_ratio.  Runs in a CHILD
    process: the virtual device count is an XLA init-time flag this
    process's backend has already fixed."""
    import subprocess
    import sys

    env = dict(os.environ)
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    env["JAX_PLATFORMS"] = "cpu"
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--mc-overlap-child"],
            env=env, capture_output=True, text=True, timeout=600,
        )
    except subprocess.TimeoutExpired:
        return
    line = (out.stdout.strip().splitlines() or [""])[-1]
    try:
        child = json.loads(line)
    except ValueError:
        return
    results.update(child)


def _mc_overlap_child() -> None:
    """The bench_mc_overlap child body (8 virtual CPU devices)."""
    import gc

    jax.config.update("jax_platforms", "cpu")
    from incubator_brpc_tpu.parallel.mc_dispatch import (
        dispatch_chunks,
        dispatch_overlapped_chunks,
        propose_dispatch,
    )
    from incubator_brpc_tpu.rpc import (
        Channel,
        Server,
        ServerOptions,
        device_method,
    )
    from incubator_brpc_tpu.rpc.device_method import (
        DeviceMethod,
        register_device_method,
    )
    from incubator_brpc_tpu.transport.mc_worker import (
        SESSION_WIDTH,
        _scale_psum_kernel,
        session_expected,
    )

    register_device_method(
        "dsvc", "scale",
        DeviceMethod(_scale_psum_kernel, width=SESSION_WIDTH, chunkable=True),
    )
    servers = []
    for i in range(2):
        s = Server(ServerOptions(
            device_index=i + 1, usercode_inline=True,
            enable_collective_service=True, collective_max_concurrency=0,
        ))
        s.add_service("dsvc", {"scale": device_method(
            _scale_psum_kernel, width=SESSION_WIDTH, chunkable=True
        )})
        assert s.start(0)
        servers.append(s)
    chans = []
    for s in servers:
        ch = Channel()
        assert ch.init(f"127.0.0.1:{s.port}")
        chans.append(ch)
    party_ids = [jax.devices()[1].id, jax.devices()[2].id]
    operands = [bytes(range(64)), bytes(range(128, 224))]
    steps = 24
    want = session_expected(operands, steps)

    def one(double_buffer: bool) -> float:
        t0 = time.perf_counter()
        out = propose_dispatch(
            chans, party_ids, "dsvc", "scale", operands,
            steps=steps, proposer_index=None, timeout_ms=120000,
            chunks=4, double_buffer=double_buffer,
        )
        dt = time.perf_counter() - t0
        assert out["results"] == want
        return dt / steps * 1e3

    per_step = {False: [], True: []}
    one(False), one(True)  # warm both compile caches
    # ratio from the DOUBLE-BUFFERED arm's deltas only: the bvars are
    # process-lifetime Adders, and the serialized control's chunks (never
    # overlapped by construction) would dilute the ratio ~2x
    db_chunks = db_overlapped = 0
    for rep in range(3):
        order = (False, True) if rep % 2 == 0 else (True, False)
        for db in order:
            gc.collect()
            c0, o0 = (
                dispatch_chunks.get_value(),
                dispatch_overlapped_chunks.get_value(),
            )
            per_step[db].append(one(db))
            if db:
                db_chunks += dispatch_chunks.get_value() - c0
                db_overlapped += (
                    dispatch_overlapped_chunks.get_value() - o0
                )
    ratio = db_overlapped / db_chunks if db_chunks else 0.0
    print(json.dumps({
        "mc_session_serialized_per_step_ms": round(min(per_step[False]), 3),
        "mc_session_overlapped_per_step_ms": round(min(per_step[True]), 3),
        "mc_dispatch_overlap_ratio": round(ratio, 3),
    }))
    for s in servers:
        s.stop()
        s.join(timeout=5)


def bench_mc_quantized(results: dict) -> None:
    """Quantized collective A/B (parallel/quantized.py): a 2-party
    in-process pmean session at 4 KiB width, exact float32 vs int8 vs
    int4 block-quantized — per-step ms per mode (interleaved best-of-3)
    plus the wire-bytes ratios the quantization buys.  Runs in a CHILD
    process (virtual 8-device CPU mesh, same reason as bench_mc_overlap)."""
    import subprocess
    import sys

    env = dict(os.environ)
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    env["JAX_PLATFORMS"] = "cpu"
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--mc-quantized-child"],
            env=env, capture_output=True, text=True, timeout=600,
        )
    except subprocess.TimeoutExpired:
        return
    line = (out.stdout.strip().splitlines() or [""])[-1]
    try:
        child = json.loads(line)
    except ValueError:
        return
    results.update(child)


def _mc_quantized_child() -> None:
    """The bench_mc_quantized child body (8 virtual CPU devices)."""
    import gc

    import numpy as np

    jax.config.update("jax_platforms", "cpu")
    from incubator_brpc_tpu.parallel import quantized as Q
    from incubator_brpc_tpu.parallel.mc_collective import _pmean_dm
    from incubator_brpc_tpu.parallel.mc_dispatch import propose_dispatch
    from incubator_brpc_tpu.rpc import Channel, Server, ServerOptions
    from incubator_brpc_tpu.rpc.device_method import register_device_method

    width = 4096  # 1024 floats, 32 scale blocks of 32
    register_device_method("_collective", "pmean", _pmean_dm(width))
    servers = []
    for i in range(2):
        s = Server(ServerOptions(
            device_index=i + 1, usercode_inline=True,
            enable_collective_service=True, collective_max_concurrency=0,
        ))
        assert s.start(0)
        servers.append(s)
    chans = []
    for s in servers:
        ch = Channel()
        assert ch.init(f"127.0.0.1:{s.port}")
        chans.append(ch)
    party_ids = [jax.devices()[1].id, jax.devices()[2].id]
    rng = np.random.default_rng(11)
    rows = [
        (rng.standard_normal(width // 4) * (i + 1)).astype(np.float32)
        for i in range(2)
    ]
    operands = [r.tobytes() for r in rows]
    steps = 16
    wire = {}
    exact_results = {}

    def one(mode: str) -> float:
        t0 = time.perf_counter()
        out = propose_dispatch(
            chans, party_ids, "_collective", "pmean", operands,
            steps=steps, proposer_index=None, timeout_ms=120000,
            quantize=mode,
        )
        dt = time.perf_counter() - t0
        wire[mode] = out["wire_bytes"]
        if mode == "none":
            exact_results["rows"] = [
                np.frombuffer(r, dtype=np.float32) for r in out["results"]
            ]
        else:
            # correctness rides along: quantized error inside the bound
            bound = Q.pmean_error_bound(rows, steps, mode)
            for got, ref in zip(out["results"], exact_results["rows"]):
                err = np.abs(
                    np.frombuffer(got, dtype=np.float32) - ref
                ).max()
                assert err <= bound, (mode, float(err), bound)
        return dt / steps * 1e3

    modes = ("none", "int8", "int4")
    per_step = {m: [] for m in modes}
    for m in modes:
        one(m)  # warm every compile cache (exact first: the oracle)
    for _rep in range(3):
        for m in modes:
            gc.collect()
            per_step[m].append(one(m))
    print(json.dumps({
        "mc_quantized_exact_per_step_ms": round(min(per_step["none"]), 3),
        "mc_quantized_int8_per_step_ms": round(min(per_step["int8"]), 3),
        "mc_quantized_int4_per_step_ms": round(min(per_step["int4"]), 3),
        "mc_quantized_int8_wire_ratio": round(wire["int8"] / wire["none"], 4),
        "mc_quantized_int4_wire_ratio": round(wire["int4"] / wire["none"], 4),
        "mc_quantized_width_bytes": width,
    }))
    for s in servers:
        s.stop()
        s.join(timeout=5)


def bench_host_calibration(results: dict) -> None:
    """A fixed unit of single-thread CPU work (native CRC32C over 64 MiB),
    repeated across the run. Every other row shares this host's one core
    with unknown co-tenants; the calibration row turns 'the numbers moved'
    into 'the HOST moved': ms-per-unit medians across rounds are directly
    comparable, and a high max/min spread flags a contended capture."""
    from incubator_brpc_tpu import native

    if not native.NATIVE_AVAILABLE:
        return
    blob = b"c" * (64 << 20)
    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        native.crc32c(blob)
        times.append((time.perf_counter() - t0) * 1e3)
    _record("host_calibration_ms", times)
    # median, NOT min: a contended window usually still has one quiet
    # iteration, so min stays flat exactly when the row should alarm
    results["host_calibration_ms"] = sorted(times)[len(times) // 2]


# Baseline commentary for every row — printed as PROSE (stderr), never
# inside the JSON blob: the compact metric line must survive a tail read.
BASELINES = {
    "large_frame": "brpc same-machine >=32KB multi-conn ~2.3 GB/s (docs/cn/benchmark.md:106); on-device HBM echo vs network loopback — not apples-to-apples",
    "rpc_echo": "brpc single-thread echo 200-300 ns/req, 3-5 M qps/thread on 24 HT cores with client and server on separate cores (docs/cn/benchmark.md:57); native_pump_ns is the comparable (pipelined, no interpreter) with client AND server sharing this host's single core; rpc_echo_us crosses the Python L5 API into the native plane",
    "rpc_echo_prpc": "the canonical baidu_std wire on the native plane: brpc's headline 200-300 ns/req, 3-5 M qps/thread single-thread echo IS this protocol (docs/cn/benchmark.md:57); prpc_pump_ns is the interpreter-free comparable (client+server share one core here), rpc_echo_prpc_us crosses the Python L5 per call",
    "native_echo_32k": "brpc same-machine >=32KB single-conn ~0.8 GB/s, multi-conn ~2.3 GB/s (docs/cn/benchmark.md:106); ours is one connection, bidirectional bytes",
    "pooled_32k": "the reference's pooled multi-connection ~2.3 GB/s row: ours is 4 concurrent connections x 32 KiB echoes, bidirectional bytes, on one shared core",
    "stream": "brpc same-machine single-conn ~0.8 GB/s (docs/cn/benchmark.md:106)",
    "link_stream": "transport data rate through the device link, shared-device fast path (rdma_performance analog; reference publishes no in-tree RDMA number); wire vs local is judged on link_stream_wire_vs_local_pct — the median of per-PAIR ratios from interleaved reps, so co-tenant drift on this shared core hits both modes equally (the r05 6.6% gap came from sequential blocks measured minutes apart)",
    "native_echo_32k_r06": "the r05 'regression' (2.403 GB/s vs r03's 3.165) tracks the HOST, not the code: r05's capture ran at host_calibration_ms 12.64, and on a container whose calibration row reads 6.3-6.4 ms the same code measures 3.08 median / 3.21 best-of-3 — at or above the r03 level. Judge this row TOGETHER with host_calibration_ms: on one shared core the GB/s moves ~inversely with that row, so a capture whose calibration sits near 12 ms should be read as ~0.75x of its quiet-host value before calling a code regression",
    "device_rpc": "bounded by window/RTT on this tunneled chip (~0.5-1s submission+readback per round under load, high variance); concurrent calls micro-batch into vmapped dispatches, which cuts dispatch COUNT — the win shows where dispatch cost dominates (local PCIe), not through a tunnel",
    "fabricnet_mfu": "vs v5e peak bf16 197 TFLOP/s",
    "native_pump_notes": "template-pack + pooled body reuse + meta memo; 1 shared core, both sides",
    "native_pump_scaling": "r05 one-core baseline: 544 ns/echo, ~1.9 M qps with client AND server sharing ONE core, and BENCH_r04's flat 1/2/4-conn curve (~1 M qps each — one loop thread was the ceiling). The matrix is R reactors x C connections (aggregate qps); scaling_efficiency = best 4-reactor / best 1-reactor. The reference scales 3-5 M qps/thread across 24 cores (docs/cn/benchmark.md:112-122); on this host the reachable ratio is capped by host_cpus, since the C client pumps burn the same cores the reactors serve from",
    "prpc_traced_pump": "every frame of the traced pump carries RpcRequestMeta trace fields 3-6 + the field-9 sampled bit (ISSUE 15) and is decoded/dispatched natively with rpcz ON — the per-frame cost over the bare pump is the trace decode + the name-keyed memo (the byte memo can't hit per-call span ids) + the 64-byte (vs 48) completion record + forced span collection on the drain; bare/traced rounds are INTERLEAVED so prpc_traced_vs_bare survives shared-host noise; acceptance ~1.15x of the bare pump with cb_frames == 0. Measured at introduction on this 2-core container (host_calibration_ms ~6.5): prpc_traced_pump_ns 1735 vs bare 1631 interleaved = 1.06x, cb_frames 0. BEFORE this PR any nonzero trace id routed the frame to the ~35 us Python route: same host (2026-08-03, host_calibration_ms ~6.4), a traced per-call echo was ~186 us vs ~92 us untraced per-call and ~1.1 us bare pump, with cb_frames == 100% of traced requests — the before-number for the Python-routed traced echo",
    "prpc_pump_telemetry": "prpc_pump_ns runs with the native telemetry ring ON (the default: per-method latency + sampled rpcz + limiter feedback recorded in-path); prpc_pump_notelem_ns is the same pump ring-less — the delta is the instrumentation tax (acceptance < 5%)",
    "prpc_production_shaped": "compressed and/or authenticated PRPC floods ride the native codec/auth seam end to end (PR 11); BEFORE this seam the same wire shape fell off to the ~35 us Python route — r05-era context: prpc_pump_ns 544 ns vs rpc-over-Python ~35 us, a ~60x tax on production-shaped traffic. Measured on this 2-core container at introduction (host_calibration_ms ~6.4): prpc_plain_4k_pump_ns ~2.3 us, prpc_compressed_pump_ns (snappy+auth, 4 KiB compressible) ~4.2-4.8 us = ~1.9-2.0x of the bare same-size pump (acceptance ~2x; incompressible ~1.3x, auth-only within noise of bare — the steady-state token check is one cached-verdict load), the L5 crossing rpc_echo_prpc_snappy_us ~130 us, and rpc_echo_prpc_snappy_python_us ~950 us — the Python-plane before-number for the SAME wire shape, ~200x the interpreter-free pump and ~7x the native L5 row; compare medians WITH host_calibration_ms context per the PR 10 re-anchor note",
    "fabricnet_overlap": "T3 compute/communication overlap (ISSUE 13): serialized vs overlapped are the SAME sliced microbatch schedule (identical ops, bit-identical losses — asserted) differing only in the optimization_barrier that pins each slice's gradient collectives before the next slice's forward; the idle-gap row is per-step ms the barrier costs. HONEST HOST NOTE: on a 1-device mesh the cross-party psums are trivial, and on a 2-core CPU container XLA has no second compute stream to hide collectives behind — the gap here measures scheduling freedom, not ICI overlap; read it as overlapped >= serialized plus the multi-device mc_session rows, with host_calibration_ms context, per the PR 10 re-anchor discipline. The config stays at bench scale everywhere (a scaled-down CPU config measured the gap inside noise); on a CPU backend only the scan length halves (fabricnet_overlap_config records dims + scan length; emulated bf16 runs this config at ~20 s/step) — compare rows only at matching configs. The >= 85% MFU acceptance belongs to a real multi-chip mesh. Measured at introduction on this CPU container (host_calibration_ms 6.27): serialized 20078 ms/step vs overlapped 19859 at n10 (idle gap 219 ms/step) and 20445 vs 20370 at the shipped n5 (gap 74 ms/step), bit-identical losses both; mc_session chunked 2-party A/B: per-step ms statistically tied across schedules on this host (0.56-1.03 run-to-run spread swamps the delta — CPU XLA runs collectives inline, nothing to hide them behind), while mc_dispatch_overlap_ratio 0.92-0.94 (double-buffered arm only — the serialized control's never-overlapped chunks are excluded from the denominator) shows the schedule itself kept ~15/16 chunk dispatches in flight past the predecessor's ack",
    "mc_session_overlap": "chunked collective sessions (chunks=4, 2-party, virtual 8-device CPU mesh in a child process): serialized acks every chunk of step k before dispatching step k+1 (jax.block_until_ready per chunk — host-visible ack barrier); double-buffered keeps two step slots in flight, chunk ack j of step k gating only slice j of step k+1 at the dataflow level with zero added host sync. mc_dispatch_overlap_ratio is the measured fraction of chunk dispatches fired while the same slice's predecessor was still in flight",
    "mc_quantized": "block-wise quantized pmean sessions (EQuARX analog, parallel/quantized.py): 2-party, 4 KiB rows, 16 steps, exact float32 vs int8 vs int4 with per-block power-of-two scales, interleaved best-of-3. The LOAD-BEARING numbers are the wire ratios (int8 ~0.258x, int4 ~0.133x of exact bytes — computed from the actual gathered array sizes) and the in-run error-bound assertion; the per-step ms rows are regression tracking ONLY on this host: a CPU backend pays the quantize/dequantize arithmetic but moves 'wire' bytes through shared memory, so the byte reduction cannot show as time here — the ms win belongs to a bandwidth-bound mesh (read with host_calibration_ms context, PR 10 re-anchor discipline). Measured at introduction: exact 0.821 / int8 0.831 / int4 0.865 ms/step — statistically tied, as predicted for a compute-bound host",
    "analysis_layer_cost": "ISSUE 12 re-run after fabricscan landed — static analysis is lint/build-time only, and the only wire-path code changes were the pump's tbus frame cap and the snappy table mask, both single O(1) compares: at host_calibration_ms 6.25 (quiet host), prpc_pump_ns 1137 (notelem 1156), prpc_plain_4k_pump_ns 2793, prpc_compressed_pump_ns 5180 (snappy+auth, compressible 4 KiB) = 1.85x plain, native_pump_ns 1295 — the plain + compressed pump headline sits inside the PR 11 introduction envelope (~2.3 us plain / 1.9-2.0x compressed at calibration ~6.4), i.e. no measurable hot-path cost from the analysis layer",
}


def main() -> None:
    import sys

    results: dict = {}
    bench_host_calibration(results)
    bench_device_echo(results)
    bench_rpc_echo(results)
    bench_native_plane(results)
    bench_prpc_production(results)
    bench_device_rpc(results)
    bench_device_link(results)
    bench_fabricnet(results)
    bench_fabricnet_overlap(results)
    bench_mc_overlap(results)
    bench_mc_quantized(results)

    gbps = results["large_frame_gbps"]
    baseline_gbps = 2.3  # reference same-machine large-payload max (BASELINE.md)

    # prose first, on stderr: context a human wants, a tail reader skips
    for key, note in BASELINES.items():
        print(f"# baseline {key}: {note}", file=sys.stderr)

    print(
        json.dumps(
            {
                "metric": "tensor_echo_throughput_detail",
                "detail": {
                    "device": str(jax.devices()[0]),
                    "small_frame_us": round(results["small_frame_us"], 2),
                    "small_frame_qps": round(results["small_frame_qps"]),
                    # native data plane (src/tbnet) — the sanctioned fast path
                    "rpc_echo_us": round(results.get("rpc_echo_us", 0.0), 1) or None,
                    "rpc_echo_qps": round(results.get("rpc_echo_qps", 0)) or None,
                    "native_pump_ns": round(results.get("native_pump_ns", 0)) or None,
                    "native_pump_qps": round(results.get("native_pump_qps", 0)) or None,
                    # baidu_std on the native plane (PRPC in C++ end to end)
                    "rpc_echo_prpc_us": (
                        round(results["rpc_echo_prpc_us"], 1)
                        if "rpc_echo_prpc_us" in results
                        else None
                    ),
                    "prpc_pump_ns": round(results.get("prpc_pump_ns", 0)) or None,
                    "prpc_pump_qps": round(results.get("prpc_pump_qps", 0)) or None,
                    # production-shaped traffic on the native plane
                    "prpc_plain_4k_pump_ns": (
                        round(results.get("prpc_plain_4k_pump_ns", 0)) or None
                    ),
                    "prpc_compressed_pump_ns": (
                        round(results.get("prpc_compressed_pump_ns", 0))
                        or None
                    ),
                    "prpc_compressed_incompressible_pump_ns": (
                        round(
                            results.get(
                                "prpc_compressed_incompressible_pump_ns", 0
                            )
                        )
                        or None
                    ),
                    "prpc_auth_pump_ns": (
                        round(results.get("prpc_auth_pump_ns", 0)) or None
                    ),
                    "prpc_compressed_vs_plain_ratio": (
                        round(
                            results.get("prpc_compressed_vs_plain_ratio", 0), 2
                        )
                        or None
                    ),
                    "rpc_echo_prpc_snappy_us": (
                        round(results.get("rpc_echo_prpc_snappy_us", 0.0), 1)
                        or None
                    ),
                    "rpc_echo_prpc_snappy_python_us": (
                        round(
                            results.get("rpc_echo_prpc_snappy_python_us", 0.0),
                            1,
                        )
                        or None
                    ),
                    # the same pump without the completion-record ring:
                    # prpc_pump_ns minus this is the telemetry tax
                    "prpc_pump_notelem_ns": (
                        round(results.get("prpc_pump_notelem_ns", 0)) or None
                    ),
                    "native_echo_32k_gbps": (
                        round(results["native_echo_32k_gbps"], 3)
                        if "native_echo_32k_gbps" in results
                        else None
                    ),
                    "pooled_32k_gbps": (
                        round(results["pooled_32k_gbps"], 3)
                        if "pooled_32k_gbps" in results
                        else None
                    ),
                    # reactors × connections matrix: key "<R>r" maps conn
                    # count -> aggregate qps on an R-reactor server
                    "native_pump_scaling_qps": {
                        f"{r}r": {
                            str(c): round(
                                results[f"native_pump_qps_r{r}c{c}"]
                            )
                            for c in (1, 2, 4)
                            if f"native_pump_qps_r{r}c{c}" in results
                        }
                        for r in (1, 2, 4)
                        if any(
                            f"native_pump_qps_r{r}c{c}" in results
                            for c in (1, 2, 4)
                        )
                    },
                    # best 4-reactor qps / best 1-reactor qps — > 1 means
                    # the one-core ceiling is broken; ~min(4, host_cpus/2)
                    # is the loopback bound (client pumps burn cores too)
                    "scaling_efficiency": (
                        round(results["native_pump_scaling_efficiency"], 2)
                        if "native_pump_scaling_efficiency" in results
                        else None
                    ),
                    # context for the scaling row: the client pump threads
                    # and the server reactors share these cores, so the
                    # reachable efficiency is bounded by host_cpus, not by
                    # the reactor count
                    "host_cpus": os.cpu_count(),
                    # pure-Python plane (the portable fallback)
                    "rpc_echo_py_us": round(results["rpc_echo_py_us"], 1),
                    "rpc_echo_py_qps": round(results["rpc_echo_py_qps"]),
                    "stream_gbps": round(results["stream_gbps"], 3),
                    "device_rpc_us": round(results["device_rpc_us"], 1),
                    "device_rpc_qps": round(results["device_rpc_qps"]),
                    "device_link_echo_us": round(results["device_link_echo_us"], 1),
                    "link_stream_gbps": round(results["link_stream_gbps"], 3),
                    "link_stream_wire_gbps": round(
                        results["link_stream_wire_gbps"], 3
                    ),
                    # median of per-pair (wire run)/(local run) ratios from
                    # INTERLEAVED reps — host drift cancels; >= 95 meets
                    # the round-4 "wire within 5% of local" target
                    "link_stream_wire_vs_local_pct": round(
                        results["link_stream_wire_vs_local_pct"], 1
                    ),
                    "fabricnet_step_ms": round(results["fabricnet_step_ms"], 2),
                    # null (not 0) when cost analysis was unavailable
                    "fabricnet_tflops": (
                        round(results["fabricnet_tflops"], 1)
                        if "fabricnet_tflops" in results
                        else None
                    ),
                    "fabricnet_mfu_pct": (
                        round(results["fabricnet_mfu_pct"], 1)
                        if "fabricnet_mfu_pct" in results
                        else None
                    ),
                    # T3 overlap scheduler A/B (same process, interleaved
                    # best-of-3): serialized pins each microbatch slice's
                    # gradient collectives before the next slice's
                    # forward; overlapped drops the barrier — the gap is
                    # per-step idle the overlap removes
                    "fabricnet_overlap_config": results.get(
                        "fabricnet_overlap_config"
                    ),
                    "fabricnet_sched_serialized_step_ms": (
                        round(results["fabricnet_sched_serialized_step_ms"], 2)
                        if "fabricnet_sched_serialized_step_ms" in results
                        else None
                    ),
                    "fabricnet_sched_overlapped_step_ms": (
                        round(results["fabricnet_sched_overlapped_step_ms"], 2)
                        if "fabricnet_sched_overlapped_step_ms" in results
                        else None
                    ),
                    "fabricnet_overlap_idle_gap_ms": (
                        round(results["fabricnet_overlap_idle_gap_ms"], 2)
                        if "fabricnet_overlap_idle_gap_ms" in results
                        else None
                    ),
                    "fabricnet_overlap_mfu_pct": (
                        round(results["fabricnet_overlap_mfu_pct"], 1)
                        if "fabricnet_overlap_mfu_pct" in results
                        else None
                    ),
                    "fabricnet_sched_identical": results.get(
                        "fabricnet_sched_identical"
                    ),
                    # chunked collective session A/B (2-party, chunks=4,
                    # child process on the virtual 8-device mesh)
                    "mc_session_serialized_per_step_ms": results.get(
                        "mc_session_serialized_per_step_ms"
                    ),
                    "mc_session_overlapped_per_step_ms": results.get(
                        "mc_session_overlapped_per_step_ms"
                    ),
                    "mc_dispatch_overlap_ratio": results.get(
                        "mc_dispatch_overlap_ratio"
                    ),
                    # raw repetition stats per row: median/min/max/n —
                    # noise and regressions are distinguishable now
                    "spread": SAMPLES,
                    # fixed CPU work unit (native CRC32C / 64 MiB): the
                    # host-load normalizer for every row above. Compare
                    # medians across rounds; a wide min/max marks a
                    # contended capture window.
                    "host_calibration_ms": results.get("host_calibration_ms"),
                },
            }
        )
    )

    # the compact headline line prints LAST: a tail read of any length
    # that reaches one line gets the metrics that matter
    print(
        json.dumps(
            {
                "metric": "tensor_echo_throughput",
                "value": round(gbps, 3),
                "unit": "GB/s",
                "vs_baseline": round(gbps / baseline_gbps, 3),
                "headline": {
                    "small_frame_us": round(results["small_frame_us"], 2),
                    "native_pump_ns": round(results.get("native_pump_ns", 0)) or None,
                    "prpc_pump_ns": round(results.get("prpc_pump_ns", 0)) or None,
                    "prpc_compressed_pump_ns": (
                        round(results.get("prpc_compressed_pump_ns", 0))
                        or None
                    ),
                    "rpc_echo_us": round(results.get("rpc_echo_us", 0.0), 1) or None,
                    "rpc_echo_qps": round(results.get("rpc_echo_qps", 0)) or None,
                    "stream_gbps": round(results["stream_gbps"], 3),
                    "link_stream_gbps": round(results["link_stream_gbps"], 3),
                    "device_rpc_qps": round(results["device_rpc_qps"]),
                    "fabricnet_step_ms": round(results["fabricnet_step_ms"], 2),
                    "fabricnet_mfu_pct": (
                        round(results["fabricnet_mfu_pct"], 1)
                        if "fabricnet_mfu_pct" in results
                        else None
                    ),
                    "fabricnet_overlap_mfu_pct": (
                        round(results["fabricnet_overlap_mfu_pct"], 1)
                        if "fabricnet_overlap_mfu_pct" in results
                        else None
                    ),
                    "fabricnet_overlap_idle_gap_ms": (
                        round(results["fabricnet_overlap_idle_gap_ms"], 2)
                        if "fabricnet_overlap_idle_gap_ms" in results
                        else None
                    ),
                    "host_calibration_ms": results.get("host_calibration_ms"),
                },
            }
        )
    )


if __name__ == "__main__":
    import sys as _sys

    if "--mc-overlap-child" in _sys.argv:
        _mc_overlap_child()
    elif "--mc-quantized-child" in _sys.argv:
        _mc_quantized_child()
    else:
        main()
